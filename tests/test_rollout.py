"""Safe deployment plane (ISSUE 15): version-identity plumbing, the
precise /drain contract, the shadow lane, and the full deployment chaos
drills (testing/chaos_matrix.py::DEPLOY_MATRIX) — a bad deploy must
auto-rollback with zero client-visible failures and a pinned
flight-recorder trace; a good deploy must roll every member."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.obs.aggregate import FleetAggregator
from spotter_tpu.serving import wire
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.replica_pool import ReplicaPool
from spotter_tpu.serving.rollout import (
    DONE,
    RolloutController,
    ShadowLane,
    _norm_detections,
)
from spotter_tpu.serving.router import make_router_app
from spotter_tpu.serving.standalone import make_app
from spotter_tpu.testing.chaos_matrix import (
    DEPLOY_MATRIX,
    run_deploy_scenario,
)
from spotter_tpu.testing.stub_engine import StubEngine, StubHttpClient

PAYLOAD = {"image_urls": ["http://example.com/room.jpg"]}


def _stub_detector(version: str | None = None, service_ms: float = 0.0):
    engine = StubEngine(service_ms=service_ms)
    if version is not None:
        engine.metrics.set_identity(version=version)
    engine.metrics.set_identity(weights_digest=engine.weights_digest())
    return AmenitiesDetector(
        engine, MicroBatcher(engine, max_delay_ms=1.0), StubHttpClient()
    )


async def _stub_server(version: str | None = None):
    det = _stub_detector(version)
    server = TestServer(make_app(detector=det))
    await server.start_server()
    return det, server, f"http://{server.host}:{server.port}"


# ---------------------------------------------------------------------------
# version identity (satellite 4)


def test_version_header_and_identity_at_replica():
    """Every /detect outcome carries X-Spotter-Version, and the /metrics
    identity block carries build version + weights digest."""

    async def run():
        det, server, _url = await _stub_server(version="v7")
        async with TestClient(server) as client:
            resp = await client.post("/detect", json=PAYLOAD)
            assert resp.status == 200
            assert resp.headers[wire.VERSION_HEADER] == "v7"
            # a shed outcome names its version too
            await det.drain()
            resp = await client.post("/detect", json=PAYLOAD)
            assert resp.status == 503
            assert resp.headers[wire.VERSION_HEADER] == "v7"
            m = await client.get("/metrics")
            snap = await m.json()
            assert snap["replica"]["version"] == "v7"
            assert snap["replica"]["weights_digest"]
            assert len(snap["replica"]["weights_digest"]) == 12
        await det.aclose()

    asyncio.run(run())


def test_version_default_and_healthz():
    """Unset SPOTTER_TPU_BUILD_VERSION -> "dev"; /healthz reports it."""

    async def run():
        det, server, _url = await _stub_server()
        async with TestClient(server) as client:
            h = await client.get("/healthz")
            body = await h.json()
            assert body["version"] == "dev"
        await det.aclose()

    asyncio.run(run())


def test_router_version_passthrough_and_fanin_join():
    """Single-owner responses pass the version header through unchanged;
    a fan-in across mixed-version owners joins the distinct versions —
    the mixed-version-window signal a client can observe directly."""

    async def run():
        det1, server1, url1 = await _stub_server(version="v1")
        det2, server2, url2 = await _stub_server(version="v2")
        pool = ReplicaPool([url1, url2], health_interval_s=30.0)
        app = make_router_app(
            pool, aggregator=FleetAggregator(lambda: [], interval_s=0.0)
        )
        async with TestClient(TestServer(app)) as client:
            # single URL -> single owner -> passthrough (one version)
            resp = await client.post("/detect", json=PAYLOAD)
            assert resp.status == 200
            assert resp.headers[wire.VERSION_HEADER] in ("v1", "v2")
            # 16 distinct URLs rendezvous-spread over both owners: the
            # fan-in joins both contributing versions
            many = {
                "image_urls": [
                    f"http://example.com/img-{i}.jpg" for i in range(16)
                ]
            }
            resp = await client.post("/detect", json=many)
            assert resp.status == 200
            versions = set(
                resp.headers[wire.VERSION_HEADER].split(",")
            )
            assert versions == {"v1", "v2"}
        await pool.stop()
        for det, server in ((det1, server1), (det2, server2)):
            await server.close()
            await det.aclose()

    asyncio.run(run())


def test_fleet_edge_version_passthrough():
    from spotter_tpu.serving.fleet import make_fleet_app, static_fleet

    async def run():
        det, server, url = await _stub_server(version="v3")
        controller = static_fleet([url], [])
        app = make_fleet_app(
            controller,
            aggregator=FleetAggregator(lambda: [], interval_s=0.0),
        )
        async with TestClient(TestServer(app)) as client:
            for _ in range(40):  # wait for the pool's health promotion
                resp = await client.post("/detect", json=PAYLOAD)
                if resp.status == 200:
                    break
                await asyncio.sleep(0.05)
            assert resp.status == 200
            assert resp.headers[wire.VERSION_HEADER] == "v3"
        await server.close()
        await det.aclose()

    asyncio.run(run())


def test_pool_learns_version_from_response_header():
    async def run():
        det, server, url = await _stub_server(version="v9")
        pool = ReplicaPool([url], health_interval_s=30.0)
        assert pool.replica_for(url).version == ""
        await pool.request("/detect", PAYLOAD)
        assert pool.replica_for(url).version == "v9"
        await pool.stop()
        await server.close()
        await det.aclose()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# precise drain (satellite 3)


def test_drain_deadline_and_in_flight_count():
    async def run():
        det, server, _url = await _stub_server()
        async with TestClient(server) as client:
            resp = await client.post("/drain", json={"deadline_ms": 500})
            assert resp.status == 200
            body = await resp.json()
            assert body["status"] == "drained"
            assert body["in_flight"] == 0
            assert body["queued_failed"] == 0
        await det.aclose()

    asyncio.run(run())


def test_drain_rejects_bad_deadline():
    async def run():
        det, server, _url = await _stub_server()
        async with TestClient(server) as client:
            resp = await client.post(
                "/drain", json={"deadline_ms": "soon"}
            )
            assert resp.status == 400
        await det.aclose()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# shadow lane units


def test_shadow_sampling_is_deterministic():
    lane = ShadowLane(pct=25.0)
    took = [lane.take() for _ in range(100)]
    assert sum(took) == 25
    # Bresenham, not random: exactly every 4th draw
    assert took[:8] == [False, False, False, True] * 2
    assert ShadowLane(pct=0.0).take() is False


def test_norm_detections_diff_semantics():
    a = [{"detections": [{"label": "tv", "score": 0.901}]}]
    b = [{"detections": [{"label": "tv", "score": 0.899}]}]
    c = [{"detections": [{"label": "oven", "score": 0.901}]}]
    assert _norm_detections(a) == _norm_detections(b)  # 2dp-stable
    assert _norm_detections(a) != _norm_detections(c)  # real diff
    assert _norm_detections([]) == []


def test_rollout_with_no_members_is_done():
    async def run():
        pool = ReplicaPool(["http://127.0.0.1:1"], health_interval_s=30.0)
        ctl = RolloutController(
            pool, members=[], spawner=lambda: None, version_to="v2"
        )
        assert await ctl.run() == DONE
        await pool.stop()

    asyncio.run(run())


def test_rollout_prom_counter_labels():
    from spotter_tpu.obs import prom

    text = prom.render(
        {"rollout": {"rollouts_total": {"promoted": 1, "rolled_back": 2}}}
    )
    assert (
        'spotter_tpu_rollout_rollouts_total{verdict="promoted"} 1' in text
    )
    assert (
        'spotter_tpu_rollout_rollouts_total{verdict="rolled_back"} 2'
        in text
    )


# ---------------------------------------------------------------------------
# the deployment chaos drills (the acceptance surface)


def _run_row(name: str) -> dict:
    sc = next(s for s in DEPLOY_MATRIX if s.name == name)
    report = asyncio.run(run_deploy_scenario(sc))
    assert report["ok"], json.dumps(
        {k: v for k, v in report.items() if k != "replica_snapshots"},
        indent=2,
        default=str,
    )
    return report


def test_deploy_good_rolls_everyone():
    report = _run_row("good-deploy")
    assert report["state"] == "done"
    assert report["fleet_versions"] == ["v2", "v2", "v2"]
    assert report["client_failures"] == 0
    assert report["rollouts_total"] == {"promoted": 1, "rolled_back": 0}


def test_deploy_slow_canary_rolls_back_on_p99():
    report = _run_row("bad-deploy-slow")
    assert report["reason"] == "p99_vs_baseline"
    assert report["client_failures"] == 0
    assert report["trace_pinned"]
    # the old fleet is intact after the rollback
    assert report["fleet_size"] == 3
    assert all(v == "v1" for v in report["fleet_versions"])


def test_deploy_flaky_canary_rolls_back_on_error_rate():
    report = _run_row("bad-deploy-flaky")
    assert report["reason"] == "error_rate"
    assert report["client_failures"] == 0


def test_deploy_corrupt_canary_rolls_back_via_crc():
    report = _run_row("bad-deploy-corrupt")
    assert report["reason"] == "error_rate"
    assert report["invalid_responses"] > 0
    assert report["client_failures"] == 0


def test_deploy_wrong_output_caught_by_shadow_lane():
    report = _run_row("bad-deploy-wrong-output")
    assert report["reason"] == "shadow_diff"
    assert report["shadow"]["diffs_total"] >= 2
    # shadow traffic is never client-visible: zero failures even though
    # the canary answered garbage the whole time
    assert report["client_failures"] == 0


def test_spawn_timeout_rolls_back():
    """A canary that never turns ready must roll back (spawn_timeout),
    not hang the rollout."""

    class DeadHandle:
        url = "http://127.0.0.1:1"  # reserved port: never healthy
        version = "v2"

        def shutdown(self) -> None:
            pass

    async def run():
        det, server, url = await _stub_server(version="v1")
        pool = ReplicaPool([url], health_interval_s=0.05)
        ctl = RolloutController(
            pool,
            members=[url],
            spawner=lambda: DeadHandle(),
            version_to="v2",
            spawn_wait_s=0.5,
            tick_s=0.05,
        )
        state = await asyncio.wait_for(ctl.run(), timeout=10.0)
        assert state == "rolled_back"
        assert ctl.rollback_reason == "spawn_timeout"
        # the old member still serves
        assert pool.replica_for(url) is not None
        await pool.stop()
        await server.close()
        await det.aclose()

    asyncio.run(run())
