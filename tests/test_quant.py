"""int8 quantization path (utils/quant.py, SPOTTER_TPU_INT8=1).

Numerical contract: dynamic per-tensor activation + per-out-channel weight
symmetric quantization. The hard accuracy gate on real weights is the
golden-box test (±1 px, tests/test_golden_boxes.py); these tests pin the
machinery — scales, error bounds, param-tree invariance — on random data.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np

from spotter_tpu.utils.quant import (
    int8_conv,
    quantize_activation,
    quantize_weight,
)


def test_int8_min_batch_guard(monkeypatch):
    """SPOTTER_TPU_INT8_MIN_BATCH (ISSUE 3): int8 regresses under-filled MXU
    batches (R101 bucket 4: 33.0 vs 18.7 ms/call — BASELINE round 5), so the
    guard keeps buckets below the floor bf16 even with INT8=1. Batch is a
    static jit shape, so the decision is per compiled bucket; batch=None
    (non-serving callers) keeps the old behavior."""
    from spotter_tpu.utils import quant

    monkeypatch.setattr(quant, "INT8", True)
    monkeypatch.setattr(quant, "INT8_DENSE", True)
    monkeypatch.setattr(quant, "INT8_MIN_BATCH", 8)
    assert quant.int8_wanted(128) and quant.int8_wanted(128, batch=None)
    assert not quant.int8_wanted(128, batch=4)  # latency-SLO bucket stays bf16
    assert quant.int8_wanted(128, batch=8)
    assert quant.int8_wanted(128, batch=16)
    assert not quant.int8_dense_wanted(128, batch=4)
    assert quant.int8_dense_wanted(128, batch=8)
    # floor of 1 disables the guard (the CI golden gate runs batch 1)
    monkeypatch.setattr(quant, "INT8_MIN_BATCH", 1)
    assert quant.int8_wanted(128, batch=1)
    # channel floor still applies regardless of batch
    assert not quant.int8_wanted(8, batch=16)


def test_quantize_weight_per_channel_roundtrip():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((3, 3, 32, 16)) * 0.1, jnp.float32)
    wq, scale = quantize_weight(w)
    assert wq.dtype == jnp.int8 and scale.shape == (16,)
    err = np.abs(np.asarray(wq, np.float32) * np.asarray(scale) - np.asarray(w))
    # symmetric rounding: per-element error <= scale/2 of that channel
    assert (err <= np.asarray(scale)[None, None, None, :] * 0.5 + 1e-7).all()


def test_quantize_activation_per_sample_scale():
    """Scales are per leading-axis sample: a batch-mate's outlier must not
    coarsen this sample's quantization (serving determinism — a request's
    boxes cannot depend on what the MicroBatcher co-batched with it)."""
    x = jnp.asarray([[1.0, -3.0], [0.5, 2.0]], jnp.float32)
    xq, s = quantize_activation(x)
    assert xq.dtype == jnp.int8 and s.shape == (2, 1)
    np.testing.assert_allclose(
        np.asarray(s)[:, 0], [3.0 / 127.0, 2.0 / 127.0], rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(xq, np.float32) * np.asarray(s),
        np.asarray(x),
        atol=float(np.asarray(s).max()) / 2 + 1e-7,
    )
    # sample 0 unchanged when its batch-mate changes
    x2 = x.at[1].mul(100.0)
    xq2, s2 = quantize_activation(x2)
    np.testing.assert_array_equal(np.asarray(xq2[0]), np.asarray(xq[0]))
    np.testing.assert_allclose(float(s2[0, 0]), float(s[0, 0]), rtol=1e-7)


def test_int8_conv_approximates_float_conv():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 3, 64, 32)) * 0.05, jnp.float32)
    ref = jax.lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)], dimension_numbers=("NHWC", "HWIO", "NHWC")
    )
    got = int8_conv(x, w, (1, 1), [(1, 1), (1, 1)], jnp.float32)
    assert got.dtype == jnp.float32 and got.shape == ref.shape
    # per-tensor int8: relative error on the output scale, not per element
    rel = np.abs(np.asarray(got) - np.asarray(ref)).max() / np.abs(
        np.asarray(ref)
    ).max()
    assert rel < 0.02, rel


def test_int8_conv_gradients_are_straight_through():
    """The backward pass must be the float conv's (STE): round/clip are flat
    almost everywhere, so without it SPOTTER_TPU_INT8=1 under the train step
    would silently zero every conv-kernel gradient."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((1, 8, 8, 16)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 3, 16, 8)) * 0.1, jnp.float32)
    cot = jnp.asarray(rng.standard_normal((1, 8, 8, 8)), jnp.float32)

    def loss_q(xx, ww):
        return jnp.sum(int8_conv(xx, ww, (1, 1), [(1, 1), (1, 1)], jnp.float32) * cot)

    def loss_f(xx, ww):
        y = jax.lax.conv_general_dilated(
            xx, ww, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return jnp.sum(y * cot)

    gq = jax.grad(loss_q, (0, 1))(x, w)
    gf = jax.grad(loss_f, (0, 1))(x, w)
    for a, b in zip(gq, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)
        assert float(jnp.abs(a).max()) > 0  # not silently zeroed


def test_quant_dense_matches_nn_dense_param_tree_and_output():
    """QuantDense with the knob off must BE nn.Dense: same param paths,
    shapes, and (given the same params) identical outputs — the ViT torch-
    parity tests rest on this."""
    from flax import linen as nn

    from spotter_tpu.models.layers import QuantDense

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((2, 5, 32)), jnp.float32)
    ref = nn.Dense(16)
    got = QuantDense(16)
    pref = ref.init(jax.random.PRNGKey(7), x)["params"]
    pgot = got.init(jax.random.PRNGKey(7), x)["params"]
    assert jax.tree_util.tree_structure(pref) == jax.tree_util.tree_structure(pgot)

    def by_path(tree):
        return sorted(
            (jax.tree_util.keystr(path), leaf.shape)
            for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
        )

    assert by_path(pref) == by_path(pgot)
    np.testing.assert_allclose(
        np.asarray(ref.apply({"params": pref}, x)),
        np.asarray(got.apply({"params": pref}, x)),
        rtol=1e-6,
        atol=1e-6,
    )


def test_int8_dense_approximates_and_ste_grads():
    from spotter_tpu.utils.quant import int8_dense

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((48, 24)) * 0.1, jnp.float32)
    ref = x @ w
    got = int8_dense(x, w, jnp.float32)
    rel = np.abs(np.asarray(got) - np.asarray(ref)).max() / np.abs(np.asarray(ref)).max()
    assert rel < 0.02, rel

    gq = jax.grad(lambda a, b: jnp.sum(int8_dense(a, b, jnp.float32) ** 2), (0, 1))(x, w)
    # STE: gradients of sum(f^2) differ between quantized/float f, so check
    # against the float-backward applied at the quantized output cotangent
    cot = 2 * got
    _, vjp = jax.vjp(lambda a, b: a @ b, x, w)
    gf = vjp(cot)
    for a, b in zip(gq, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_quant_dense_takes_int8_path_and_stays_close(monkeypatch):
    """SPOTTER_TPU_INT8_DENSE end-to-end at the layer (ISSUE 9 satellite):
    with the knobs armed QuantDense must actually route through int8_dense
    (output differs from the exact float matmul — the path is live) while
    staying within quantization tolerance of it (the parity half)."""
    from flax import linen as nn

    from spotter_tpu.models import layers
    from spotter_tpu.utils import quant

    monkeypatch.setattr(quant, "INT8", True)
    monkeypatch.setattr(quant, "INT8_DENSE", True)
    monkeypatch.setattr(quant, "INT8_MIN_CH", 8)
    monkeypatch.setattr(quant, "INT8_MIN_BATCH", 1)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 7, 32)), jnp.float32)
    ref = nn.Dense(16)
    got = layers.QuantDense(16)
    params = ref.init(jax.random.PRNGKey(11), x)["params"]
    exact = np.asarray(ref.apply({"params": params}, x))
    quantized = np.asarray(got.apply({"params": params}, x))
    assert not np.allclose(quantized, exact, atol=1e-7)  # int8 path is live
    rel = np.abs(quantized - exact).max() / np.abs(exact).max()
    assert rel < 0.02, rel
    # below the batch floor the layer must stay exactly bf16/float
    monkeypatch.setattr(quant, "INT8_MIN_BATCH", 8)
    np.testing.assert_allclose(
        np.asarray(got.apply({"params": params}, x)), exact, atol=1e-6
    )


def test_int8_dense_env_score_box_parity_bf16_reference():
    """bf16-vs-int8-dense parity on the tiny RT-DETR forward (ISSUE 9
    satellite, ROADMAP item 1 leftover): SPOTTER_TPU_INT8_DENSE=1 (which
    quantizes the attention/FFN projections on top of the convs) must keep
    scores and boxes within tolerance of the float reference, and must not
    change the param tree. Knobs are import-time, hence the subprocess."""
    code = """
import os, numpy as np, jax, jax.numpy as jnp
from spotter_tpu.models.zoo import tiny_rtdetr_config
from spotter_tpu.models.rtdetr import RTDetrDetector
cfg = tiny_rtdetr_config()
m = RTDetrDetector(cfg)
x = np.random.default_rng(0).standard_normal((1, 64, 64, 3)).astype(np.float32)
p = m.init(jax.random.PRNGKey(0), x)["params"]
out = m.apply({"params": p}, x)
leaf_paths = sorted(
    "/".join(str(k) for k in path)
    for path, _ in jax.tree_util.tree_flatten_with_path(p)[0]
)
import hashlib
print("TREE", hashlib.sha256("\\n".join(leaf_paths).encode()).hexdigest()[:16])
print("BOX", float(jnp.abs(out["pred_boxes"]).mean()))
print("SCORE", float(jax.nn.sigmoid(out["logits"]).max()))
"""
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SPOTTER_TPU_INT8_MIN_CH": "8",
        "SPOTTER_TPU_INT8_MIN_BATCH": "1",
    }
    env_base.pop("PALLAS_AXON_POOL_IPS", None)
    outs = {}
    for tag, int8, dense in (("bf16", "0", "0"), ("int8dense", "1", "1")):
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env={
                **env_base,
                "SPOTTER_TPU_INT8": int8,
                "SPOTTER_TPU_INT8_DENSE": dense,
            },
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = dict(
            ln.split(" ", 1) for ln in proc.stdout.splitlines() if " " in ln
        )
        outs[tag] = lines
    assert outs["bf16"]["TREE"] == outs["int8dense"]["TREE"], (
        "param tree changed under INT8_DENSE"
    )
    box_ref, box_q = (float(outs[t]["BOX"]) for t in ("bf16", "int8dense"))
    score_ref, score_q = (
        float(outs[t]["SCORE"]) for t in ("bf16", "int8dense")
    )
    # boxes are sigmoid-bounded cxcywh in (0,1): 0.05 aggregate drift on the
    # random-init tiny model is the same bar the conv-only test pins
    assert abs(box_ref - box_q) < 0.05, (box_ref, box_q)
    assert abs(score_ref - score_q) < 0.05, (score_ref, score_q)


def test_int8_env_keeps_param_tree_and_output_close():
    """SPOTTER_TPU_INT8=1 must not change the param tree (checkpoints stay
    loadable) and the tiny-model forward must stay close to float. The knob
    is read at import, so this runs in a subprocess with a forced channel
    floor low enough to trigger on the tiny config."""
    code = """
import os, numpy as np, jax, jax.numpy as jnp
from spotter_tpu.models.zoo import tiny_rtdetr_config
from spotter_tpu.models.rtdetr import RTDetrDetector
cfg = tiny_rtdetr_config()
m = RTDetrDetector(cfg)
x = np.random.default_rng(0).standard_normal((1, 64, 64, 3)).astype(np.float32)
p = m.init(jax.random.PRNGKey(0), x)["params"]
out = m.apply({"params": p}, x)
leaf_paths = sorted(
    "/".join(str(k) for k in path)
    for path, _ in jax.tree_util.tree_flatten_with_path(p)[0]
)
import hashlib
print("TREE", hashlib.sha256("\\n".join(leaf_paths).encode()).hexdigest()[:16])
print("BOX", float(jnp.abs(out["pred_boxes"]).mean()))
"""
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SPOTTER_TPU_INT8_MIN_CH": "8",
        # the subprocess forward runs batch 1 — disable the small-batch
        # guard so INT8=1 actually takes the quantized path under test
        "SPOTTER_TPU_INT8_MIN_BATCH": "1",
    }
    env_base.pop("PALLAS_AXON_POOL_IPS", None)
    outs = {}
    for flag in ("0", "1"):
        proc = subprocess.run(
            [sys.executable, "-c", code],
            env={**env_base, "SPOTTER_TPU_INT8": flag},
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = dict(
            ln.split(" ", 1) for ln in proc.stdout.splitlines() if " " in ln
        )
        outs[flag] = lines
    assert outs["0"]["TREE"] == outs["1"]["TREE"], "param tree changed under INT8"
    b0, b1 = float(outs["0"]["BOX"]), float(outs["1"]["BOX"])
    # boxes are sigmoid-bounded; int8 drift on a random-init tiny model stays
    # small in aggregate
    assert abs(b0 - b1) < 0.05, (b0, b1)
