"""MicroBatcher shutdown and admission edges (ISSUE 1 satellite): stop()
racing a full queue, submit() after stop(), slot release on batch exception,
bounded-queue shedding, and deadline-expired entries skipped by the pump."""

import asyncio
import threading

import numpy as np
import pytest
from PIL import Image

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.serving.resilience import (
    CircuitBreaker,
    Deadline,
    DrainingError,
    QueueFullError,
)
from spotter_tpu.testing import faults

DETS = [{"label": "tv", "score": 0.9, "box": [0.0, 0.0, 5.0, 5.0]}]


class FakeEngine:
    def __init__(self):
        self.metrics = Metrics()
        self.batch_buckets = (1, 2, 4)
        self.calls = []

    def detect(self, images):
        self.calls.append(len(images))
        return [list(DETS) for _ in images]


class BlockingEngine(FakeEngine):
    def __init__(self):
        super().__init__()
        self.release = threading.Event()

    def detect(self, images):
        self.release.wait(timeout=10.0)
        return super().detect(images)


def _img():
    return Image.fromarray(np.zeros((8, 8, 3), np.uint8))


def _batcher(engine, **kwargs):
    kwargs.setdefault("max_delay_ms", 1.0)
    kwargs.setdefault("breaker", CircuitBreaker(threshold=100, metrics=engine.metrics))
    return MicroBatcher(engine, **kwargs)


def test_submit_after_stop_raises_not_silently_restarts():
    engine = FakeEngine()
    batcher = _batcher(engine)

    async def run():
        assert await batcher.submit(_img()) == DETS
        await batcher.stop()
        with pytest.raises(DrainingError):
            await batcher.submit(_img())
        assert batcher._pump_task is None  # stop() is sticky: no hidden pump
        # an explicit start() re-opens (symmetric lifecycle)
        await batcher.start()
        assert await batcher.submit(_img()) == DETS
        await batcher.stop()

    asyncio.run(run())
    assert engine.metrics.snapshot()["shed_total"] == 1


def test_stop_racing_full_queue_fails_all_pending():
    """stop() with a wedged batch in flight, one batch in the pump's hand,
    and entries still queued: the in-flight batch finishes, everything else
    fails promptly — no submit() caller waits forever."""
    engine = BlockingEngine()
    batcher = _batcher(engine, max_batch=1, max_in_flight=1, max_queue=8)

    async def run():
        r1 = asyncio.create_task(batcher.submit(_img()))
        await asyncio.sleep(0.1)  # r1's batch now blocks inside detect()
        r2 = asyncio.create_task(batcher.submit(_img()))
        r3 = asyncio.create_task(batcher.submit(_img()))
        await asyncio.sleep(0.1)  # r2 held by the pump at the slot; r3 queued
        stop = asyncio.create_task(batcher.stop())
        await asyncio.sleep(0.05)
        engine.release.set()  # let the in-flight batch finish
        await stop
        return r1, r2, r3

    r1, r2, r3 = asyncio.run(run())
    assert r1.result() == DETS  # dispatched work completes
    for r in (r2, r3):
        with pytest.raises(DrainingError, match="MicroBatcher stopped"):
            r.result()


def test_slot_released_on_batch_exception():
    """Two consecutive failing batches with max_in_flight=1: a leaked slot
    would wedge the second submit forever."""
    engine = FakeEngine()
    batcher = _batcher(engine, max_batch=1, max_in_flight=1)

    async def run():
        with faults.inject(engine_error=2):
            for _ in range(2):
                with pytest.raises(RuntimeError, match="injected engine failure"):
                    await asyncio.wait_for(batcher.submit(_img()), timeout=5.0)
        ok = await asyncio.wait_for(batcher.submit(_img()), timeout=5.0)
        await batcher.stop()
        return ok

    assert asyncio.run(run()) == DETS
    assert engine.metrics.snapshot()["errors_total"] == 2


def test_bounded_queue_sheds_with_retry_hint():
    engine = BlockingEngine()
    batcher = _batcher(engine, max_batch=1, max_in_flight=1, max_queue=1)

    async def run():
        r1 = asyncio.create_task(batcher.submit(_img()))
        await asyncio.sleep(0.1)  # in engine
        r2 = asyncio.create_task(batcher.submit(_img()))
        await asyncio.sleep(0.05)  # held by pump
        r3 = asyncio.create_task(batcher.submit(_img()))
        await asyncio.sleep(0.05)  # fills the depth-1 queue
        with pytest.raises(QueueFullError) as exc_info:
            await batcher.submit(_img())
        assert exc_info.value.status == 429
        assert exc_info.value.retry_after_s > 0
        engine.release.set()
        results = await asyncio.gather(r1, r2, r3)
        await batcher.stop()
        return results

    results = asyncio.run(run())
    assert all(r == DETS for r in results)
    assert engine.metrics.snapshot()["shed_total"] == 1


def test_pump_skips_deadline_expired_entries():
    """An entry whose caller already gave up must not consume a device call."""
    engine = BlockingEngine()
    batcher = _batcher(engine, max_batch=1, max_in_flight=1)

    async def run():
        r1 = asyncio.create_task(batcher.submit(_img()))
        await asyncio.sleep(0.1)  # r1 wedged in engine
        from spotter_tpu.serving.resilience import DeadlineExceededError

        with pytest.raises(DeadlineExceededError):
            await batcher.submit(_img(), deadline=Deadline.after(0.1))
        engine.release.set()
        await r1
        # give the pump a moment to pick up (and discard) the dead entry
        await asyncio.sleep(0.2)
        await batcher.stop()

    asyncio.run(run())
    # only r1 reached the engine; the expired entry was skipped
    assert engine.calls == [1]
    assert engine.metrics.snapshot()["deadline_exceeded_total"] == 1


def test_drain_flushes_then_rejects():
    engine = FakeEngine()
    batcher = _batcher(engine, max_batch=2, max_delay_ms=20.0)

    async def run():
        pending = [asyncio.create_task(batcher.submit(_img())) for _ in range(3)]
        await asyncio.sleep(0)  # let the submits enqueue
        summary = await batcher.drain(timeout_s=5.0)
        assert summary["status"] == "drained"
        results = await asyncio.gather(*pending)
        assert all(r == DETS for r in results)
        with pytest.raises(DrainingError):
            await batcher.submit(_img())

    asyncio.run(run())


def test_poison_max_splits_env(monkeypatch):
    """SPOTTER_TPU_POISON_MAX_SPLITS defaults and env override."""
    from spotter_tpu.engine.errors import DEFAULT_POISON_MAX_SPLITS

    engine = FakeEngine()
    assert _batcher(engine).poison_max_splits == DEFAULT_POISON_MAX_SPLITS
    monkeypatch.setenv("SPOTTER_TPU_POISON_MAX_SPLITS", "2")
    assert _batcher(engine).poison_max_splits == 2


def test_two_poisons_both_isolated():
    """Bisect isolation handles more than one poison per batch: both fail
    with PoisonImageError, both innocents succeed, breaker stays closed."""
    from spotter_tpu.engine.errors import PoisonImageError

    engine = FakeEngine()
    breaker = CircuitBreaker(threshold=2, metrics=engine.metrics)
    batcher = _batcher(engine, max_batch=4, max_delay_ms=100.0, breaker=breaker)
    images = [_img() for _ in range(4)]
    faults.poison_image(images[0])
    faults.poison_image(images[3])

    async def run():
        with faults.inject(poison_item=1):
            results = await asyncio.gather(
                *(batcher.submit(im) for im in images), return_exceptions=True
            )
        await batcher.stop()
        return results

    results = asyncio.run(run())
    assert isinstance(results[0], PoisonImageError)
    assert isinstance(results[3], PoisonImageError)
    assert results[1] == DETS and results[2] == DETS
    assert breaker.state == CircuitBreaker.CLOSED
    assert engine.metrics.snapshot()["poison_isolated_total"] == 2


def test_splits_budget_bounds_isolation_depth():
    """With a 1-deep split budget a poisoned batch of 4 can only reach
    2-image sub-batches: the poisoned half fails raw (nothing isolated to a
    single image), the clean half still succeeds."""
    engine = FakeEngine()
    breaker = CircuitBreaker(threshold=100, metrics=engine.metrics)
    batcher = _batcher(
        engine, max_batch=4, max_delay_ms=100.0, breaker=breaker, poison_max_splits=1
    )
    images = [_img() for _ in range(4)]
    faults.poison_image(images[1])

    async def run():
        with faults.inject(poison_item=1):
            results = await asyncio.gather(
                *(batcher.submit(im) for im in images), return_exceptions=True
            )
        await batcher.stop()
        return results

    results = asyncio.run(run())
    from spotter_tpu.engine.errors import PoisonImageError

    # poisoned half [0, 1] fails (raw, not PoisonImageError); clean half succeeds
    assert isinstance(results[0], RuntimeError)
    assert isinstance(results[1], RuntimeError)
    assert not isinstance(results[0], PoisonImageError)
    assert results[2] == DETS and results[3] == DETS
    snap = engine.metrics.snapshot()
    assert snap["poison_isolated_total"] == 0
    assert snap["batch_retries_total"] == 1
