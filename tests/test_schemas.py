"""Wire-contract tests for the /detect schemas (reference: schemas.py:6-32)."""

import pytest
from pydantic import ValidationError

from spotter_tpu.schemas import (
    DetectionErrorResult,
    DetectionRequest,
    DetectionResponse,
    DetectionResult,
    DetectionSuccessResult,
)


def test_request_validates_urls():
    req = DetectionRequest.model_validate({"image_urls": ["http://example.com/a.jpg"]})
    assert str(req.image_urls[0]) == "http://example.com/a.jpg"


def test_request_rejects_non_urls():
    with pytest.raises(ValidationError):
        DetectionRequest.model_validate({"image_urls": ["not a url"]})


def test_response_round_trip_mixed_results():
    resp = DetectionResponse(
        amenities_description="The property contains: TV, sofa.",
        images=[
            DetectionSuccessResult(
                url="http://example.com/a.jpg",
                detections=[DetectionResult(label="TV", box=[1.0, 2.0, 3.0, 4.0])],
                labeled_image_base64="aGk=",
            ),
            DetectionErrorResult(url="http://example.com/b.jpg", error="HTTP Error: 404"),
        ],
    )
    data = resp.model_dump(exclude_none=True)
    assert data["images"][0]["detections"][0]["label"] == "TV"
    assert data["images"][1]["error"].startswith("HTTP Error:")
    # Wire shape must be exactly what chilir/spotter clients expect: the
    # serving layers dump with exclude_none, so a non-degraded response
    # carries NO `degraded` key (ISSUE 8 marker contract).
    assert set(data.keys()) == {"amenities_description", "images"}
    assert set(data["images"][0].keys()) == {"url", "detections", "labeled_image_base64"}
    assert set(data["images"][1].keys()) == {"url", "error"}


def test_response_degraded_marker_contract():
    """The brownout `degraded` markers are additive: absent unless set,
    a plain string list when a concession shaped the response."""
    resp = DetectionResponse(
        amenities_description="No relevant amenities detected.",
        images=[],
        degraded=["bucket_cap", "stale"],
    )
    data = resp.model_dump(exclude_none=True)
    assert data["degraded"] == ["bucket_cap", "stale"]
    plain = DetectionResponse(
        amenities_description="No relevant amenities detected.", images=[]
    )
    assert "degraded" not in plain.model_dump(exclude_none=True)


def test_taxonomy_contract():
    from spotter_tpu.taxonomy import AMENITIES_MAPPING

    assert len(AMENITIES_MAPPING) == 22
    assert AMENITIES_MAPPING["couch"] == "sofa"
    assert AMENITIES_MAPPING["tv"] == "TV"
    assert AMENITIES_MAPPING["car"] == "parking"
    assert "remote" not in AMENITIES_MAPPING
