"""Numerical parity: Flax YolosDetector vs HF torch YolosForObjectDetection.

Tiny random-init config at the native image size (the serving path always
feeds the trained size, so position tables are exercised without
interpolation), with and without mid position embeddings.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import YolosConfig as HFYolosConfig
from transformers.models.yolos.modeling_yolos import YolosForObjectDetection

from spotter_tpu.convert.torch_to_jax import convert_state_dict
from spotter_tpu.convert.yolos_rules import yolos_rules
from spotter_tpu.models.configs import YolosConfig
from spotter_tpu.models.yolos import YolosDetector


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def _tiny_hf_config(use_mid):
    return HFYolosConfig(
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=48,
        image_size=[32, 48],
        patch_size=8,
        num_detection_tokens=5,
        use_mid_position_embeddings=use_mid,
        num_labels=7,
    )


@pytest.mark.parametrize("use_mid", [True, False])
def test_yolos_parity(use_mid):
    hf_cfg = _tiny_hf_config(use_mid)
    torch.manual_seed(0)
    model = YolosForObjectDetection(hf_cfg).eval()
    # zeros-initialized tokens/tables would hide wiring bugs; randomize them
    with torch.no_grad():
        for p in (
            model.vit.embeddings.cls_token,
            model.vit.embeddings.detection_tokens,
            model.vit.embeddings.position_embeddings,
        ):
            p.uniform_(-0.5, 0.5)
        if use_mid:
            model.vit.encoder.mid_position_embeddings.uniform_(-0.5, 0.5)

    cfg = YolosConfig.from_hf(hf_cfg)
    params = convert_state_dict(model.state_dict(), yolos_rules(cfg), strict=True)

    rng = np.random.default_rng(1)
    x = rng.uniform(-1, 1, size=(2, 3, 32, 48)).astype(np.float32)
    with torch.no_grad():
        tout = model(torch.from_numpy(x))

    jout = YolosDetector(cfg).apply({"params": params}, np.transpose(x, (0, 2, 3, 1)))

    np.testing.assert_allclose(
        np.asarray(jout["pred_boxes"]), tout.pred_boxes.numpy(), atol=2e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(jout["logits"]), tout.logits.numpy(), atol=5e-4, rtol=1e-3
    )
