"""Numerical parity: Flax OwlViTDetector vs HF torch OwlViTForObjectDetection.

Tiny random-init config; queries with varying EOT positions and padding so
the causal+padding text mask and EOT pooling are exercised, plus the
text-embed caching split (encode_text once, vision-only forward after).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import OwlViTConfig as HFOwlViTConfig
from transformers.models.owlvit.modeling_owlvit import OwlViTForObjectDetection

from spotter_tpu.convert.owlvit_rules import owlvit_rules
from spotter_tpu.convert.torch_to_jax import convert_state_dict
from spotter_tpu.models.configs import OwlViTConfig
from spotter_tpu.models.owlvit import OwlViTDetector


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def _tiny_hf_config():
    return HFOwlViTConfig(
        text_config=dict(
            vocab_size=99,
            hidden_size=16,
            intermediate_size=24,
            num_hidden_layers=2,
            num_attention_heads=2,
            max_position_embeddings=8,
        ),
        vision_config=dict(
            hidden_size=20,
            intermediate_size=28,
            num_hidden_layers=2,
            num_attention_heads=2,
            image_size=32,
            patch_size=8,
        ),
        projection_dim=16,
    )


@pytest.fixture(scope="module")
def tiny_pair():
    hf_cfg = _tiny_hf_config()
    torch.manual_seed(0)
    model = OwlViTForObjectDetection(hf_cfg).eval()
    cfg = OwlViTConfig.from_hf(hf_cfg)
    params = convert_state_dict(model.state_dict(), owlvit_rules(cfg), strict=True)
    return model, cfg, params


# (Q, T): nonzero first token (HF derives query_mask from it), EOT (max id 98)
# at varying positions, zero padding after.
QUERY_IDS = np.array(
    [
        [5, 7, 98, 0, 0, 0, 0, 0],
        [5, 9, 12, 98, 0, 0, 0, 0],
        [5, 98, 0, 0, 0, 0, 0, 0],
    ],
    dtype=np.int64,
)


def test_owlvit_detection_parity(tiny_pair):
    model, cfg, params = tiny_pair
    rng = np.random.default_rng(1)
    pixels = rng.uniform(-1, 1, size=(2, 3, 32, 32)).astype(np.float32)
    attn = (QUERY_IDS != 0).astype(np.int64)

    with torch.no_grad():
        tout = model(
            input_ids=torch.from_numpy(np.tile(QUERY_IDS, (2, 1))),  # per-image tile
            pixel_values=torch.from_numpy(pixels),
            attention_mask=torch.from_numpy(np.tile(attn, (2, 1))),
        )

    jout = OwlViTDetector(cfg).apply(
        {"params": params},
        np.transpose(pixels, (0, 2, 3, 1)),
        QUERY_IDS.astype(np.int32),
        attn.astype(np.int32),
        method=OwlViTDetector.detect_with_text,
    )

    np.testing.assert_allclose(
        np.asarray(jout["pred_boxes"]), tout.pred_boxes.numpy(), atol=2e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(jout["logits"]), tout.logits.numpy(), atol=5e-4, rtol=1e-3
    )


def test_owlvit_cached_query_path_matches(tiny_pair):
    """Build-time encode_text + vision-only __call__ == fused forward."""
    _, cfg, params = tiny_pair
    module = OwlViTDetector(cfg)
    attn = (QUERY_IDS != 0).astype(np.int32)
    ids = QUERY_IDS.astype(np.int32)

    fused = module.apply(
        {"params": params},
        np.zeros((1, 32, 32, 3), np.float32),
        ids,
        attn,
        method=OwlViTDetector.detect_with_text,
    )
    qe = module.apply({"params": params}, ids, attn, method=OwlViTDetector.encode_text)
    assert np.asarray(qe).shape == (3, cfg.projection_dim)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(qe), axis=-1), np.ones(3), atol=1e-5
    )
    split = module.apply(
        {"params": params}, np.zeros((1, 32, 32, 3), np.float32), np.asarray(qe)
    )
    np.testing.assert_allclose(
        np.asarray(split["logits"]), np.asarray(fused["logits"]), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(split["pred_boxes"]), np.asarray(fused["pred_boxes"]), atol=1e-5
    )


def test_owlv2_detection_parity():
    """OWLv2 = OWL-ViT + objectness head, owlv2.* checkpoint prefix."""
    from transformers import Owlv2Config as HFOwlv2Config
    from transformers.models.owlv2.modeling_owlv2 import Owlv2ForObjectDetection

    hf_cfg = HFOwlv2Config(
        text_config=dict(
            vocab_size=99, hidden_size=16, intermediate_size=24,
            num_hidden_layers=2, num_attention_heads=2, max_position_embeddings=8,
        ),
        vision_config=dict(
            hidden_size=20, intermediate_size=28, num_hidden_layers=2,
            num_attention_heads=2, image_size=32, patch_size=8,
        ),
        projection_dim=16,
    )
    torch.manual_seed(0)
    model = Owlv2ForObjectDetection(hf_cfg).eval()
    cfg = OwlViTConfig.from_hf(hf_cfg)
    assert cfg.objectness
    params = convert_state_dict(model.state_dict(), owlvit_rules(cfg), strict=True)

    rng = np.random.default_rng(2)
    pixels = rng.uniform(-1, 1, size=(2, 3, 32, 32)).astype(np.float32)
    attn = (QUERY_IDS != 0).astype(np.int64)

    with torch.no_grad():
        tout = model(
            input_ids=torch.from_numpy(np.tile(QUERY_IDS, (2, 1))),
            pixel_values=torch.from_numpy(pixels),
            attention_mask=torch.from_numpy(np.tile(attn, (2, 1))),
        )

    jout = OwlViTDetector(cfg).apply(
        {"params": params},
        np.transpose(pixels, (0, 2, 3, 1)),
        QUERY_IDS.astype(np.int32),
        attn.astype(np.int32),
        method=OwlViTDetector.detect_with_text,
    )

    np.testing.assert_allclose(
        np.asarray(jout["pred_boxes"]), tout.pred_boxes.numpy(), atol=2e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(jout["logits"]), tout.logits.numpy(), atol=5e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(jout["objectness"]),
        tout.objectness_logits.numpy(),
        atol=5e-4,
        rtol=1e-3,
    )


def test_owlv2_pad_square_preprocess():
    """pad_square reports the padded-square side as target size (HF box scaling)."""
    from PIL import Image

    from spotter_tpu.ops.preprocess import OWLV2_SPEC, preprocess_image

    img = Image.fromarray(
        np.random.default_rng(0).uniform(0, 255, (30, 60, 3)).astype("uint8")
    )
    arr, mask, hw = preprocess_image(img, OWLV2_SPEC)
    assert arr.shape == (960, 960, 3) and hw == (60, 60)
    # bottom half (beyond the content's 30/60 share of the square) is gray 0.5
    gray = (0.5 - np.asarray(OWLV2_SPEC.mean)) / np.asarray(OWLV2_SPEC.std)
    np.testing.assert_allclose(arr[600:], np.broadcast_to(gray, (360, 960, 3)), atol=1e-5)


def test_owlv2_pad_square_pixel_parity_with_hf():
    """The pad-then-resize pipeline matches HF Owlv2ImageProcessor pixel-for-
    pixel (ADVICE r1: the seam between content and gray pad must not drift)."""
    from PIL import Image
    from transformers import Owlv2ImageProcessor

    from spotter_tpu.ops.preprocess import OWLV2_SPEC, preprocess_image

    rng = np.random.default_rng(7)
    for shape in ((30, 60, 3), (96, 64, 3), (960, 960, 3), (1200, 800, 3)):
        img = Image.fromarray(rng.uniform(0, 255, shape).astype("uint8"))
        ours, _, _ = preprocess_image(img, OWLV2_SPEC)
        hf = Owlv2ImageProcessor(
            image_mean=list(OWLV2_SPEC.mean), image_std=list(OWLV2_SPEC.std)
        )(images=img, return_tensors="np")["pixel_values"][0].transpose(1, 2, 0)
        np.testing.assert_allclose(ours, hf, atol=1e-5, rtol=1e-5)
