"""Preprocess numerical parity against the real HF image processors.

The reference's preprocessing IS `AutoImageProcessor`
(apps/spotter/src/spotter/serve.py:98); its golden boxes depend on the
processors' exact resample/normalize/pad behavior, and a one-pixel resize
discrepancy would silently consume the reference's entire ±1 px golden
tolerance (VERDICT r3 next #3). These tests instantiate the processor
CLASSES with each checkpoint family's published defaults (no network) and
compare `preprocess_image`'s arrays element-wise on the reference fixture
at several aspect ratios.
"""

import numpy as np
import pytest
from PIL import Image

pytest.importorskip("transformers")
from transformers import (
    DetrImageProcessor,
    Owlv2ImageProcessor,
    OwlViTImageProcessor,
    RTDetrImageProcessor,
    YolosImageProcessor,
)

from spotter_tpu.ops.preprocess import (
    DETR_SPEC,
    IMAGENET_MEAN,
    IMAGENET_STD,
    OWLV2_SPEC,
    OWLVIT_SPEC,
    RTDETR_SPEC,
    PreprocessSpec,
    preprocess_image,
    shortest_edge_size,
)

pytestmark = pytest.mark.slow

FIXTURE = "tests/test_data/test_pic.jpg"


def _variants():
    """The fixture plus resized copies covering landscape/portrait/odd sizes."""
    base = Image.open(FIXTURE).convert("RGB")
    return [
        base,
        base.resize((500, 333), Image.BILINEAR),
        base.resize((427, 640), Image.BILINEAR),  # portrait
        base.resize((97, 131), Image.BILINEAR),  # small odd dims
    ]


def _hf_chw(processor, image):
    out = processor(images=image, return_tensors="np")
    return out, np.transpose(out["pixel_values"][0], (1, 2, 0))


@pytest.mark.parametrize("idx", range(4))
def test_rtdetr_matches_hf(idx):
    img = _variants()[idx]
    arr, mask, orig = preprocess_image(img, RTDETR_SPEC)
    _, hf = _hf_chw(RTDetrImageProcessor(), img)
    assert hf.shape == arr.shape
    np.testing.assert_allclose(arr, hf, atol=1e-6)
    assert orig == (img.height, img.width)  # target_sizes semantics
    assert mask.all()


@pytest.mark.parametrize("idx", range(4))
def test_detr_shortest_edge_matches_hf(idx):
    img = _variants()[idx]
    arr, mask, orig = preprocess_image(img, DETR_SPEC)
    out, hf = _hf_chw(DetrImageProcessor(), img)
    rh, rw = hf.shape[:2]
    # HF pads to the batch max (here: the image's own resized dims); the
    # repo pads into the static (1333, 1333) bucket — compare the valid
    # region and require exact zeros (and mask zeros) outside it.
    assert (rh, rw) == shortest_edge_size((img.height, img.width), 800, 1333)
    np.testing.assert_allclose(arr[:rh, :rw], hf, atol=1e-5)
    assert (arr[rh:] == 0).all() and (arr[:, rw:] == 0).all()
    assert mask[:rh, :rw].all() and not mask[rh:].any() and not mask[:, rw:].any()
    if "pixel_mask" in out:
        np.testing.assert_array_equal(
            mask[:rh, :rw], out["pixel_mask"][0].astype(np.float32)
        )
    assert orig == (img.height, img.width)


@pytest.mark.parametrize("idx", range(4))
def test_yolos_fixed_warp_matches_hf_resample(idx):
    """YOLOS serving deliberately warp-resizes to the trained static size
    (models/zoo.py:_build_yolos — TPU static-shape policy, diverging from
    HF's dynamic mod-16 shortest-edge + pad-to-batch-max). What must still
    match HF is the resample/rescale/normalize pipeline itself, pinned here
    by forcing the HF processor to the same fixed size."""
    img = _variants()[idx]
    size = {"height": 800, "width": 1344}
    spec = PreprocessSpec(
        mode="fixed", size=(800, 1344), mean=IMAGENET_MEAN, std=IMAGENET_STD
    )
    arr, mask, orig = preprocess_image(img, spec)
    _, hf = _hf_chw(YolosImageProcessor(size=size, do_pad=False), img)
    assert hf.shape == arr.shape
    np.testing.assert_allclose(arr, hf, atol=1e-5)
    assert orig == (img.height, img.width)


@pytest.mark.parametrize("idx", range(4))
def test_owlvit_matches_hf(idx):
    img = _variants()[idx]
    arr, mask, orig = preprocess_image(img, OWLVIT_SPEC)
    _, hf = _hf_chw(OwlViTImageProcessor(), img)
    assert hf.shape == arr.shape
    np.testing.assert_allclose(arr, hf, atol=1e-5)
    assert orig == (img.height, img.width)


@pytest.mark.parametrize("idx", range(4))
def test_owlv2_matches_hf(idx):
    img = _variants()[idx]
    arr, mask, orig = preprocess_image(img, OWLV2_SPEC)
    _, hf = _hf_chw(Owlv2ImageProcessor(), img)
    assert hf.shape == arr.shape
    np.testing.assert_allclose(arr, hf, atol=2e-4)
    side = max(img.height, img.width)
    assert orig == (side, side)  # HF _scale_boxes uses the padded square
