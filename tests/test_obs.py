"""Observability tier (ISSUE 7): request-scoped tracing, flight recorder,
Prometheus exposition, and the fatal-exit trace dump — all on the CPU test
backend, model-free (stub engine).

The cross-process acceptance case reuses testing/cluster.py: a REAL
supervised stub replica serves /detect behind the in-process edge (router
and fleet apps), the trace propagates over HTTP via traceparent +
X-Request-ID, and the replica's Server-Timing merges into ONE edge trace
whose summed spans reconcile with the response latency.
"""

import asyncio
import json
import os
import re

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

os.environ["SPOTTER_TPU_TINY"] = "1"

import httpx

from spotter_tpu import obs
from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.errors import FATAL_ENGINE_EXIT_CODE, FatalEngineError
from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.obs import http as obs_http
from spotter_tpu.obs import prom
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.replica_pool import ReplicaPool
from spotter_tpu.serving.router import make_router_app
from spotter_tpu.serving.standalone import make_app
from spotter_tpu.testing import cluster, faults
from spotter_tpu.testing.stub_engine import StubEngine, StubHttpClient

# the injected device latency (ms) for attribution/reconciliation asserts:
# large enough that edge/HTTP overhead fits inside the 5% tolerance
DEVICE_MS = 150.0

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture(autouse=True)
def fresh_recorder(monkeypatch):
    """Each test gets its own recorder built from a clean env."""
    monkeypatch.delenv(obs.TRACE_RING_ENV, raising=False)
    monkeypatch.delenv(obs.TRACE_SLOWEST_K_ENV, raising=False)
    monkeypatch.delenv(obs_http.ADMIN_TOKEN_ENV, raising=False)
    obs.reset_recorder()
    obs.set_current_trace(None)
    yield
    obs.reset_recorder()
    obs.set_current_trace(None)


def _stub_detector(**batcher_kwargs) -> AmenitiesDetector:
    engine = StubEngine()
    batcher = MicroBatcher(engine, max_delay_ms=2.0, **batcher_kwargs)
    return AmenitiesDetector(engine, batcher, StubHttpClient())


# ---------------------------------------------------------------------------
# unit: trace context + propagation primitives


def test_traceparent_roundtrip():
    tr = obs.begin_trace(request_id="req-42")
    value = obs.traceparent_value(tr)
    parsed = obs.parse_traceparent(value)
    assert parsed == (tr.trace_id, tr.span_id)
    # the trace id is a deterministic function of the request id, so a
    # client that only kept its X-Request-ID can still find the trace
    assert tr.trace_id == obs.trace_id_for_request("req-42")
    for bad in (None, "", "garbage", "00-zz-11-01", "00-" + "0" * 32 + "-" + "1" * 16 + "-01"):
        assert obs.parse_traceparent(bad) is None


def test_child_trace_continues_parent():
    parent = obs.begin_trace(request_id="edge-req")
    child = obs.begin_trace(
        request_id="edge-req", traceparent=obs.traceparent_value(parent)
    )
    assert child.trace_id == parent.trace_id
    assert child.parent_span_id == parent.span_id


def test_span_capture_and_server_timing_merge():
    tr = obs.begin_trace(request_id="r")
    with obs.span(obs.FETCH, tr):
        pass
    tr.add_span_ms(obs.DEVICE, 0.0, 12.5)
    merged = obs_http.merge_server_timing(tr, "decode;dur=3.25, h2d;dur=1.0")
    assert merged == pytest.approx(4.25)
    totals = tr.stage_totals()
    assert totals[obs.DEVICE] == pytest.approx(12.5)
    assert totals[obs.DECODE] == pytest.approx(3.25)
    assert set(totals) >= {obs.FETCH, obs.DEVICE, obs.DECODE, obs.H2D}


def test_slow_stage_fault_parsing_and_delay():
    assert faults._parse_slow_stage("device:100") == {"device": 0.1}
    assert faults._parse_slow_stage("device:100;fetch:50") == {
        "device": 0.1, "fetch": 0.05,
    }
    with pytest.raises(ValueError):
        faults._parse_slow_stage("device")
    with pytest.raises(ValueError):
        faults._parse_slow_stage("device:abc")
    assert faults.stage_delay_s(obs.DEVICE) == 0.0  # no plan active
    with faults.inject(slow_stage="device:40"):
        assert faults.stage_delay_s(obs.DEVICE) == pytest.approx(0.04)
        assert faults.stage_delay_s(obs.FETCH) == 0.0


def test_slow_stage_env_activation(monkeypatch):
    monkeypatch.setenv(faults.FAULTS_ENV, "slow_stage=queue_wait:5")
    plan = faults.maybe_activate_from_env()
    try:
        assert faults.stage_delay_s(obs.QUEUE_WAIT) == pytest.approx(0.005)
    finally:
        faults._active = None
    monkeypatch.setenv(faults.FAULTS_ENV, "slow_stage=broken")
    with pytest.raises(ValueError):
        faults.maybe_activate_from_env()
    faults._active = None


# ---------------------------------------------------------------------------
# in-process: standalone server contract


def test_detect_trace_has_full_span_set_and_reconciles():
    """One /detect through the real app + batcher + stub engine: the trace
    carries every non-edge stage and its summed spans reconcile with the
    measured response latency within the 5% acceptance tolerance."""

    async def run():
        detector = _stub_detector()
        app = make_app(detector=detector)
        gaps = []
        async with TestClient(TestServer(app)) as client:
            # warmup: first-use costs (pydantic validators, PIL JPEG
            # plugin, profiler import on startup) must not land inside the
            # measured trace's unattributed gap
            warm = await client.post(
                "/detect", json={"image_urls": ["http://example.com/w.jpg"]}
            )
            assert warm.status == 200
            with faults.inject(slow_stage=f"device:{DEVICE_MS:.0f}"):
                # best-of-3: the reconciliation property is about the
                # TRACE's structure; a 1-core CI box can drop a GC pause
                # into any single request's unattributed gap
                for attempt in range(3):
                    rid = f"req-reconcile-{attempt}"
                    resp = await client.post(
                        "/detect",
                        json={"image_urls": ["http://example.com/a.jpg"]},
                        headers={obs.REQUEST_ID_HEADER: rid},
                    )
                    assert resp.status == 200
                    assert resp.headers[obs.REQUEST_ID_HEADER] == rid
                    assert obs.TRACEPARENT_HEADER in resp.headers
                    timing = resp.headers[obs_http.SERVER_TIMING_HEADER]
                    assert "device;dur=" in timing
                    (t,) = obs.get_recorder().lookup(rid)
                    names = {s["name"] for s in t["spans"]}
                    assert names >= {
                        obs.FETCH, obs.DECODE, obs.QUEUE_WAIT, obs.H2D,
                        obs.DEVICE, obs.POSTPROCESS,
                    }
                    assert len(t["spans"]) >= 6
                    assert t["duration_ms"] >= DEVICE_MS
                    # the injected latency is attributed to the device span
                    device_ms = sum(
                        s["duration_ms"] for s in t["spans"]
                        if s["name"] == obs.DEVICE
                    )
                    assert device_ms >= DEVICE_MS
                    span_sum = sum(s["duration_ms"] for s in t["spans"])
                    gaps.append(
                        abs(span_sum - t["duration_ms"]) / t["duration_ms"]
                    )
                    if gaps[-1] < 0.05:
                        break
        # the spans tile the request: sum reconciles with the response
        # latency within the 5% acceptance tolerance
        assert min(gaps) < 0.05, f"no attempt reconciled: gaps={gaps}"

    asyncio.run(run())


def test_shed_responses_echo_request_id_and_pin_trace():
    async def run():
        detector = _stub_detector()
        detector.batcher._draining = True  # check_admission -> 503 shed
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
                headers={obs.REQUEST_ID_HEADER: "req-shed"},
            )
            assert resp.status == 503
            assert resp.headers[obs.REQUEST_ID_HEADER] == "req-shed"
        snap = obs.get_recorder().snapshot()
        shed = [t for t in snap["errors"] if t["request_id"] == "req-shed"]
        assert shed and shed[0]["status"] == "shed"

    asyncio.run(run())


def test_errored_request_trace_lands_in_pinned_error_set():
    """An engine failure that kills a whole request pins its trace."""

    async def run():
        detector = _stub_detector()
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            with faults.inject(engine_error=1):
                resp = await client.post(
                    "/detect",
                    json={"image_urls": ["http://example.com/bad.jpg"]},
                    headers={obs.REQUEST_ID_HEADER: "req-errored"},
                )
            assert resp.status == 200  # per-image containment, as ever
            body = await resp.json()
            assert "Processing Error" in body["images"][0]["error"]
        snap = obs.get_recorder().snapshot()
        pinned = [t for t in snap["errors"] if t["request_id"] == "req-errored"]
        assert len(pinned) == 1
        # single-item batch: the raw error surfaces (nothing was isolated)
        assert pinned[0]["status"] == "RuntimeError"
        assert "injected engine failure" in pinned[0]["error"]

    asyncio.run(run())


def test_poison_isolation_pins_only_the_poisoned_trace():
    """The bisect-isolation case: two co-batched requests, one poisoned —
    only the poisoned item's trace carries PoisonImageError and lands in
    the pinned error set; the innocent neighbor's trace stays ok."""

    async def run():
        engine = StubEngine()
        batcher = MicroBatcher(engine, max_delay_ms=20.0)
        good = Image.fromarray(np.zeros((8, 8, 3), np.uint8))
        bad = faults.poison_image(
            Image.fromarray(np.full((8, 8, 3), 255, np.uint8))
        )
        rec = obs.get_recorder()

        async def one(img, request_id):
            tr = obs.begin_trace(request_id=request_id)
            try:
                await batcher.submit(img)
            except Exception:
                pass
            rec.record(tr)

        with faults.inject(poison_item=1):
            await asyncio.gather(
                one(good, "req-innocent"), one(bad, "req-poisoned")
            )
        await batcher.stop()
        snap = rec.snapshot()
        pinned = {t["request_id"]: t for t in snap["errors"]}
        assert "req-poisoned" in pinned
        assert "req-innocent" not in pinned
        assert pinned["req-poisoned"]["status"] == "PoisonImageError"
        ok = rec.lookup("req-innocent")
        assert ok and ok[0]["status"] == "ok"
        assert engine.metrics.snapshot()["poison_isolated_total"] == 1

    asyncio.run(run())


def test_debug_traces_admin_gated_and_lookup(monkeypatch):
    async def run():
        detector = _stub_detector()
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
                headers={obs.REQUEST_ID_HEADER: "req-gated"},
            )
            assert resp.status == 200
            monkeypatch.setenv(obs_http.ADMIN_TOKEN_ENV, "sekrit")
            assert (await client.get("/debug/traces")).status == 401
            ok = await client.get(
                "/debug/traces", headers={obs_http.ADMIN_TOKEN_HEADER: "sekrit"}
            )
            assert ok.status == 200
            snap = await ok.json()
            assert snap["enabled"] and snap["recorded_total"] >= 1
            by_id = await client.get(
                "/debug/traces?request_id=req-gated",
                headers={obs_http.ADMIN_TOKEN_HEADER: "sekrit"},
            )
            assert by_id.status == 200
            assert (await by_id.json())["traces"][0]["request_id"] == "req-gated"
            miss = await client.get(
                "/debug/traces?request_id=nope",
                headers={obs_http.ADMIN_TOKEN_HEADER: "sekrit"},
            )
            assert miss.status == 404

    asyncio.run(run())


def test_recorder_off_path_allocates_no_spans(monkeypatch):
    monkeypatch.setenv(obs.TRACE_RING_ENV, "0")
    obs.reset_recorder()
    assert not obs.get_recorder().enabled

    async def run():
        detector = _stub_detector()
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            before = obs.trace_stats()
            resp = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
                headers={obs.REQUEST_ID_HEADER: "req-off"},
            )
            assert resp.status == 200
            # correlation id still echoed with the recorder off
            assert resp.headers[obs.REQUEST_ID_HEADER] == "req-off"
            assert obs_http.SERVER_TIMING_HEADER not in resp.headers
            after = obs.trace_stats()
        assert after["spans_created"] == before["spans_created"]
        assert after["traces_created"] == before["traces_created"]
        assert obs.get_recorder().recorded_total == 0

    asyncio.run(run())


# ---------------------------------------------------------------------------
# Prometheus exposition

_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"            # metric name
    # label values are quoted strings where backslash escapes (\\, \", \n)
    # are legal per the text-format spec (ISSUE 10 satellite)
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""  # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"  # more labels
    r" (-?[0-9.e+-]+|\+Inf|-Inf|NaN)"        # value
    r"( # \{trace_id=\"[0-9a-f]+\"\} [0-9.e+-]+ [0-9.e+-]+)?$"  # exemplar
)
_TYPE_LINE = re.compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)$"
)


def _assert_parses(text: str) -> list[str]:
    lines = [ln for ln in text.splitlines() if ln.strip()]
    for ln in lines:
        if ln.startswith("#"):
            assert _TYPE_LINE.match(ln), f"bad TYPE line: {ln!r}"
        else:
            assert _METRIC_LINE.match(ln), f"bad metric line: {ln!r}"
    return lines


def test_prometheus_exposition_parses_and_json_unchanged():
    async def run():
        detector = _stub_detector()
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post(
                "/detect", json={"image_urls": ["http://example.com/a.jpg"]}
            )
            assert resp.status == 200
            # default stays JSON with every pre-existing field
            js = await (await client.get("/metrics")).json()
            for key in ("images_total", "errors_total", "breaker_state",
                        "latency_ms_p50", "shed_total", "cache_hits_total"):
                assert key in js
            # ?format=prometheus and Accept: text/plain both select text
            for kwargs in (
                {"path": "/metrics?format=prometheus"},
                {"path": "/metrics", "headers": {"Accept": "text/plain"}},
            ):
                text_resp = await client.get(
                    kwargs["path"], headers=kwargs.get("headers", {})
                )
                assert text_resp.status == 200
                assert text_resp.content_type == "text/plain"
                text = await text_resp.text()
                lines = _assert_parses(text)
                assert any(
                    ln.startswith("spotter_tpu_images_total") for ln in lines
                )
                assert "# TYPE spotter_tpu_images_total counter" in lines
                assert any(
                    ln.startswith("spotter_tpu_latency_ms_bucket{le=")
                    for ln in lines
                )
                assert any(
                    ln.startswith("spotter_tpu_latency_ms_count") for ln in lines
                )
                assert 'spotter_tpu_breaker_state_info{value="closed"} 1' in lines

    asyncio.run(run())


def test_prometheus_histogram_exemplar_carries_trace_id():
    m = Metrics()
    m.record_batch(
        4, 0.012,
        stages={obs.DECODE: 0.001, obs.DEVICE: 0.008},
        trace_id="a" * 32,
    )
    text = prom.render(m.snapshot())
    _assert_parses(text)
    ex_lines = [ln for ln in text.splitlines() if "# {trace_id=" in ln]
    assert len(ex_lines) == 1
    assert f'trace_id="{"a" * 32}"' in ex_lines[0]
    assert ex_lines[0].startswith('spotter_tpu_latency_ms_bucket{le="25"}')


def test_prometheus_renders_pool_and_fleet_snapshots():
    pool = ReplicaPool(["http://127.0.0.1:1", "http://127.0.0.1:2"])
    text = prom.render(pool.snapshot())
    lines = _assert_parses(text)
    assert any(
        ln.startswith("spotter_tpu_replicas_requests{url=") for ln in lines
    )
    from spotter_tpu.serving.fleet import static_fleet

    async def run():
        controller = static_fleet(
            ["http://127.0.0.1:1"], ["http://127.0.0.1:2"]
        )
        text = prom.render(controller.snapshot())
        lines = _assert_parses(text)
        assert any(
            ln.startswith('spotter_tpu_pool_size{pool="spot",state="ready"}')
            for ln in lines
        )

    asyncio.run(run())


def test_prometheus_label_escaping_per_text_format_spec():
    """Exposition escaping (ISSUE 10 satellite): label values carrying
    quotes, backslashes, and newlines (model names, replica URLs) must
    render per the text-format spec — backslash as \\\\, double quote as
    \\", newline as \\n — and every emitted line must still parse."""
    snapshot = {
        # string leaf -> info-style gauge with a `value` label
        "breaker_state": 'open "half"\nprobing\\mode',
        # labeled two-level map (the pool_size shape)
        "pool_size": {'spot"pool\n\\a': {"ready": 2}},
        # per-replica list labeled by url
        "replicas": [
            {"url": 'http://h/"x"\\path\nend', "requests": 3, "ok": True}
        ],
        # burn-rate map: plain labels stay plain
        "slo_burn_rate": {"fast": 0.5, "slow": 0.25},
    }
    text = prom.render(snapshot)
    lines = _assert_parses(text)
    assert (
        'spotter_tpu_breaker_state_info'
        '{value="open \\"half\\"\\nprobing\\\\mode"} 1'
    ) in lines
    assert (
        'spotter_tpu_pool_size{pool="spot\\"pool\\n\\\\a",state="ready"} 2'
    ) in lines
    assert (
        'spotter_tpu_replicas_requests'
        '{url="http://h/\\"x\\"\\\\path\\nend"} 3'
    ) in lines
    assert 'spotter_tpu_slo_burn_rate{window="fast"} 0.5' in lines
    # no raw newline may survive inside any sample line (it would split
    # the exposition mid-sample)
    for ln in lines:
        assert "\n" not in ln


def test_prometheus_escaping_round_trips_through_a_parser():
    """The escaped label value must decode back to the original string
    under the spec's unescaping rules — proof the renderer escapes, not
    mangles."""
    ugly = 'a"b\\c\nd'
    text = prom.render({"model_name": ugly})
    (line,) = [
        ln for ln in text.splitlines()
        if ln.startswith("spotter_tpu_model_name_info")
    ]
    start = line.index('value="') + len('value="')
    end = line.rindex('"}')
    escaped = line[start:end]
    decoded = (
        escaped.replace("\\\\", "\x00")
        .replace('\\"', '"')
        .replace("\\n", "\n")
        .replace("\x00", "\\")
    )
    assert decoded == ugly


# ---------------------------------------------------------------------------
# fatal-exit flight-recorder dump (the exit-85 acceptance case)


class _FatalEngine:
    """Duck-typed engine whose every detect() is a device loss."""

    def __init__(self) -> None:
        self.metrics = Metrics()
        self.batch_buckets = (1, 2, 4, 8)

    def detect(self, images):
        raise FatalEngineError("DATA_LOSS: device 0 halted (test)")


def test_fatal_exit_dumps_offending_trace(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.TRACE_DUMP_DIR_ENV, str(tmp_path))
    exits: list[int] = []

    async def run():
        batcher = MicroBatcher(
            _FatalEngine(), max_delay_ms=1.0, fatal_exit_cb=exits.append
        )
        img = Image.fromarray(np.zeros((8, 8, 3), np.uint8))
        obs.begin_trace(request_id="req-fatal")
        with pytest.raises(FatalEngineError):
            await batcher.submit(img)
        await batcher.stop()

    asyncio.run(run())
    assert exits == [FATAL_ENGINE_EXIT_CODE]
    dumps = list(tmp_path.glob("spotter-tpu-traces-*-exit85.json"))
    assert len(dumps) == 1
    payload = json.loads(dumps[0].read_text())
    pinned = [t for t in payload["errors"] if t["request_id"] == "req-fatal"]
    assert len(pinned) == 1
    assert pinned[0]["status"] == "fatal"
    assert "DATA_LOSS" in pinned[0]["error"]
    # the queue-wait span made it in before the device died
    assert any(s["name"] == obs.QUEUE_WAIT for s in pinned[0]["spans"])


def test_preemption_exit_dumps_ring(tmp_path, monkeypatch):
    from spotter_tpu.serving import lifecycle

    monkeypatch.setenv(obs.TRACE_DUMP_DIR_ENV, str(tmp_path))
    tr = obs.begin_trace(request_id="req-preempt")
    obs.get_recorder().record(tr)
    codes: list[int] = []

    async def run():
        watcher = lifecycle.PreemptionWatcher(
            on_preempt=_noop, exit_cb=codes.append, install_sigterm=False,
            poll_s=0.01, file_source=None, url_source=None,
        )
        await watcher.start()
        watcher.trigger("test preemption")
        for _ in range(100):
            if codes:
                break
            await asyncio.sleep(0.01)
        await watcher.stop()

    async def _noop():
        return None

    asyncio.run(run())
    assert codes == [lifecycle.PREEMPTED_EXIT_CODE]
    dumps = list(tmp_path.glob("spotter-tpu-traces-*-exit83.json"))
    assert len(dumps) == 1
    payload = json.loads(dumps[0].read_text())
    assert any(t["request_id"] == "req-preempt" for t in payload["ring"])


# ---------------------------------------------------------------------------
# cross-process: trace propagates router -> replica over real HTTP


@pytest.fixture(scope="module")
def slow_device_replica(tmp_path_factory):
    """One REAL supervised stub replica with 150 ms injected device latency
    (testing/cluster.py), shared by the edge-propagation tests."""
    workdir = str(tmp_path_factory.mktemp("obs-replica"))
    replicas = cluster.start_replicas(
        1, workdir,
        env={"SPOTTER_TPU_FAULTS": f"slow_stage=device:{DEVICE_MS:.0f}"},
    )
    try:
        yield replicas[0]
    finally:
        for r in replicas:
            r.shutdown()


def test_trace_propagates_router_to_replica_over_http(slow_device_replica):
    replica = slow_device_replica

    async def run():
        pool = ReplicaPool([replica.url])
        app = make_router_app(pool)
        async with TestClient(TestServer(app)) as client:
            # warmup: pays TCP connect + client-pool setup once, so the
            # measured request's unattributed network slice stays inside
            # the 5% reconciliation tolerance
            warm = await client.post(
                "/detect", json={"image_urls": ["http://img.example/0.jpg"]}
            )
            assert warm.status == 200
            gaps = []
            t = None
            for attempt in range(3):  # best-of-3, as in the in-process case
                rid = f"req-e2e-{attempt}"
                resp = await client.post(
                    "/detect",
                    json={"image_urls": ["http://img.example/1.jpg"]},
                    headers={obs.REQUEST_ID_HEADER: rid},
                )
                assert resp.status == 200
                assert resp.headers[obs.REQUEST_ID_HEADER] == rid
                # the EDGE recorder holds one trace: route spans + the
                # replica's Server-Timing merged in — every hop in one place
                (t,) = obs.get_recorder().lookup(rid)
                names = {s["name"] for s in t["spans"]}
                assert names >= {
                    obs.ROUTE, obs.FETCH, obs.DECODE, obs.QUEUE_WAIT,
                    obs.H2D, obs.DEVICE, obs.POSTPROCESS,
                }
                assert len(t["spans"]) >= 6
                device_ms = sum(
                    s["duration_ms"] for s in t["spans"]
                    if s["name"] == obs.DEVICE
                )
                assert device_ms >= DEVICE_MS
                span_sum = sum(s["duration_ms"] for s in t["spans"])
                gaps.append(
                    abs(span_sum - t["duration_ms"]) / t["duration_ms"]
                )
                if gaps[-1] < 0.05:
                    break
            assert min(gaps) < 0.05, f"no attempt reconciled: gaps={gaps}"

            # the REPLICA's own recorder has the same request, same trace
            # id, retrievable over HTTP by the client's request id
            reply = httpx.get(
                f"{replica.url}/debug/traces",
                params={"request_id": t["request_id"]},
                timeout=5.0,
            )
            assert reply.status_code == 200
            remote = reply.json()["traces"][0]
            assert remote["trace_id"] == t["trace_id"]
            assert remote["parent_span_id"] is not None

    asyncio.run(run())


def test_trace_through_fleet_edge_and_suspended_echo(slow_device_replica):
    from spotter_tpu.serving.fleet import make_fleet_app, static_fleet

    replica = slow_device_replica

    async def run():
        controller = static_fleet([replica.url], [])
        app = make_fleet_app(controller)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post(
                "/detect",
                json={"image_urls": ["http://img.example/2.jpg"]},
                headers={obs.REQUEST_ID_HEADER: "req-fleet"},
            )
            assert resp.status == 200
            assert resp.headers[obs.REQUEST_ID_HEADER] == "req-fleet"
        traces = obs.get_recorder().lookup("req-fleet")
        assert traces and {s["name"] for s in traces[0]["spans"]} >= {
            obs.ROUTE, obs.DEVICE,
        }

        # a suspended pool's fast 503 still echoes the correlation id
        # (ISSUE 7 satellite: sheds and fast-fails carry X-Request-ID)
        empty = ReplicaPool([], allow_empty=True)
        sapp = make_router_app(empty)
        async with TestClient(TestServer(sapp)) as client:
            resp = await client.post(
                "/detect",
                json={"image_urls": ["http://img.example/3.jpg"]},
                headers={obs.REQUEST_ID_HEADER: "req-suspended"},
            )
            assert resp.status == 503
            assert resp.headers[obs.REQUEST_ID_HEADER] == "req-suspended"
            assert "Retry-After" in resp.headers

    asyncio.run(run())
