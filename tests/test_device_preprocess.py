"""Parity of the split uint8/device preprocess against the host float path.

The device path (ISSUE 3) moves rescale/normalize/mask into the forward jit
and ships uint8; golden boxes ride on the host path's exact numerics
(tests/test_preprocess_hf_parity.py pins those against HF), so the device
path must reproduce them within golden tolerance — including the
shortest_edge pixel-mask case, where pad pixels must be exactly 0 (the torch
DETR processor pads AFTER normalization). Runs the real jit on CPU.
"""

import numpy as np
import pytest
from PIL import Image

from spotter_tpu.ops.preprocess import (
    DETR_SPEC,
    OWLV2_SPEC,
    RTDETR_SPEC,
    DecodePool,
    PreprocessSpec,
    batch_images,
    batch_images_host,
    batch_images_uint8,
    decode_resize_uint8,
    device_preprocess_supported,
    device_rescale_normalize,
)


def _img(h, w, seed=0):
    rng = np.random.default_rng(seed)
    return Image.fromarray(rng.integers(0, 255, size=(h, w, 3), dtype=np.uint8))


def _device_path(images, spec):
    import jax

    pixels_u8, valid, sizes = batch_images_uint8(images, spec)
    fn = jax.jit(lambda p, v: device_rescale_normalize(p, v, spec))
    pixels, masks = fn(pixels_u8, valid)
    return np.asarray(pixels), np.asarray(masks), sizes


@pytest.mark.parametrize(
    "spec", [RTDETR_SPEC, PreprocessSpec(mode="fixed", size=(64, 64),
                                         mean=(0.5, 0.4, 0.3), std=(0.2, 0.3, 0.4))]
)
def test_fixed_mode_matches_host_path(spec):
    images = [_img(48, 64), _img(100, 80, seed=1)]
    host_px, host_mask, host_sizes = batch_images(images, spec)
    dev_px, dev_mask, dev_sizes = _device_path(images, spec)
    np.testing.assert_allclose(dev_px, host_px, atol=1e-5)
    np.testing.assert_array_equal(dev_mask, host_mask)
    np.testing.assert_array_equal(dev_sizes, host_sizes)


def test_shortest_edge_matches_host_path_including_mask():
    """The DETR family's padded-bucket case: valid region matches the host
    float path, the pad region is exactly 0 (not (0 - mean)/std), and the
    pixel mask marks exactly the valid region."""
    images = [_img(480, 640), _img(1000, 500, seed=2), _img(97, 131, seed=3)]
    host_px, host_mask, host_sizes = batch_images(images, DETR_SPEC)
    dev_px, dev_mask, dev_sizes = _device_path(images, DETR_SPEC)
    np.testing.assert_allclose(dev_px, host_px, atol=1e-5)
    np.testing.assert_array_equal(dev_mask, host_mask)
    np.testing.assert_array_equal(dev_sizes, host_sizes)
    for j, img in enumerate(images):
        rh, rw = decode_resize_uint8(img, DETR_SPEC)[1]
        assert (dev_px[j, rh:] == 0).all() and (dev_px[j, :, rw:] == 0).all()
        assert dev_mask[j, :rh, :rw].all()
        assert not dev_mask[j, rh:].any() and not dev_mask[j, :, rw:].any()


def test_decode_resize_uint8_is_exact_resize_output():
    """The uint8 host half must be byte-identical to the resize the float
    path feeds its normalize — same PIL call, no extra rounding."""
    img = _img(300, 200, seed=4)
    arr_u8, valid, orig = decode_resize_uint8(img, RTDETR_SPEC)
    th, tw = RTDETR_SPEC.size
    expected = np.asarray(
        img.resize((tw, th), resample=RTDETR_SPEC.resample), dtype=np.uint8
    )
    np.testing.assert_array_equal(arr_u8, expected)
    assert arr_u8.dtype == np.uint8
    assert valid == (th, tw) and orig == (300, 200)


def test_pad_square_unsupported_and_raises():
    """OWLv2's pad_square rescales before its warp — host-float only; the
    engine must gate on device_preprocess_supported, and a direct uint8
    decode call must fail loudly rather than silently mis-normalize."""
    assert not device_preprocess_supported(OWLV2_SPEC)
    assert device_preprocess_supported(RTDETR_SPEC)
    assert device_preprocess_supported(DETR_SPEC)
    with pytest.raises(ValueError):
        decode_resize_uint8(_img(32, 32), OWLV2_SPEC)


def test_batch_images_host_matches_batch_images_with_pool():
    """The pooled host path is the same numbers as the serial one."""
    images = [_img(40, 60, seed=s) for s in range(5)]
    pool = DecodePool(workers=4)
    try:
        ref = batch_images(images, DETR_SPEC)
        pooled = batch_images_host(images, DETR_SPEC, pool=pool)
        for a, b in zip(ref, pooled):
            np.testing.assert_array_equal(a, b)
        u8_serial = batch_images_uint8(images, DETR_SPEC)
        u8_pooled = batch_images_uint8(images, DETR_SPEC, pool=pool)
        for a, b in zip(u8_serial, u8_pooled):
            np.testing.assert_array_equal(a, b)
        assert pool.queue_depth() == 0  # backlog drains back to idle
    finally:
        pool.close()


def test_decode_pool_workers_env(monkeypatch):
    monkeypatch.setenv("SPOTTER_TPU_DECODE_WORKERS", "3")
    pool = DecodePool()
    try:
        assert pool.workers == 3
        out = pool.map(lambda x: x * 2, [1, 2, 3, 4])
        assert out == [2, 4, 6, 8]  # order preserved across threads
    finally:
        pool.close()
    serial = DecodePool(workers=1)
    assert serial.map(lambda x: x + 1, [1, 2]) == [2, 3]
    serial.close()


def test_sizes_semantics_match_host():
    """target_sizes (original h, w) drive box rescale — identical either path."""
    images = [_img(123, 45, seed=7)]
    _, _, host_sizes = batch_images(images, RTDETR_SPEC)
    _, _, dev_sizes = batch_images_uint8(images, RTDETR_SPEC)
    np.testing.assert_array_equal(host_sizes, np.asarray([[123, 45]], np.float32))
    np.testing.assert_array_equal(dev_sizes.astype(np.float32), host_sizes)
