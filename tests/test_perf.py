"""Device-efficiency plane (ISSUE 10): MFU/duty-cycle accounting, the
compile ledger, HBM telemetry, SLO burn-rate, and the /debug/perf surface.

The zero-traffic cases are acceptance criteria in their own right: every
perf gauge must be present and NaN-free on an idle replica, because a
scraper hits /metrics whether or not traffic ever arrived.
"""

import asyncio
import math
import os
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

os.environ["SPOTTER_TPU_TINY"] = "1"

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.obs import perf as perf_mod
from spotter_tpu.obs import prom
from spotter_tpu.obs.perf import (
    CompileLedger,
    PerfLedger,
    SloBurn,
    peak_tflops_for,
    sample_hbm_once,
)
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.standalone import make_app
from spotter_tpu.testing import faults
from spotter_tpu.testing.stub_engine import StubEngine, StubHttpClient


def _walk_numbers(obj, path=""):
    if isinstance(obj, dict):
        for k, v in obj.items():
            yield from _walk_numbers(v, f"{path}.{k}")
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            yield from _walk_numbers(v, f"{path}[{i}]")
    elif isinstance(obj, float):
        yield path, obj


# ---------------------------------------------------------------------------
# zero-traffic safety (acceptance: idle snapshots are well-formed)


def test_zero_traffic_snapshot_is_present_and_nan_free():
    snap = Metrics().snapshot()
    for key in (
        "mfu_pct", "useful_mfu_pct", "device_duty_cycle_pct",
        "compiles_total", "compile_seconds_total",
        "program_cache_hits_total", "hbm_bytes_in_use", "hbm_peak_bytes",
        "hbm_limit_bytes", "slo_target_pct", "slo_burn_rate",
    ):
        assert key in snap, key
    assert snap["mfu_pct"] == 0.0
    assert snap["useful_mfu_pct"] == 0.0
    assert snap["device_duty_cycle_pct"] == 0.0
    assert snap["compiles_total"] == 0
    assert snap["compile_seconds_total"] == 0.0
    assert snap["hbm_bytes_in_use"] == 0
    assert snap["slo_burn_rate"] == {"fast": 0.0, "slow": 0.0}
    for path, value in _walk_numbers(snap):
        assert not math.isnan(value), f"NaN at {path}"


def test_zero_traffic_prometheus_render_has_perf_gauges():
    text = prom.render(Metrics().snapshot())
    assert "spotter_tpu_mfu_pct 0.0" in text
    assert "spotter_tpu_useful_mfu_pct 0.0" in text
    assert "spotter_tpu_device_duty_cycle_pct 0.0" in text
    assert "spotter_tpu_compiles_total 0" in text
    assert "# TYPE spotter_tpu_compiles_total counter" in text
    assert "spotter_tpu_hbm_bytes_in_use 0" in text
    assert 'spotter_tpu_slo_burn_rate{window="fast"} 0.0' in text
    assert 'spotter_tpu_slo_burn_rate{window="slow"} 0.0' in text
    assert "nan" not in text.lower()


# ---------------------------------------------------------------------------
# unit: SLO burn-rate


def test_slo_burn_idle_is_zero():
    burn = SloBurn(target_pct=99.0)
    assert burn.burn(60.0) == 0.0
    assert burn.rates() == {"fast": 0.0, "slow": 0.0}


def test_slo_burn_math():
    burn = SloBurn(target_pct=99.0)  # budget = 1%
    burn.good(99)
    burn.bad(1)  # error ratio 1% -> burn exactly 1.0
    assert burn.burn(60.0) == pytest.approx(1.0)
    burn.bad(100)  # ratio 101/200 -> burn ~50x
    assert burn.burn(60.0) == pytest.approx((101 / 200) / 0.01)
    block = burn.block()
    assert block["target_pct"] == 99.0
    assert block["fast"]["good"] == 99 and block["fast"]["bad"] == 101
    assert block["fast"]["burn_rate"] == pytest.approx(50.5, abs=0.1)


def test_slo_target_env_and_100pct_clamp(monkeypatch):
    monkeypatch.setenv(perf_mod.SLO_TARGET_PCT_ENV, "99.9")
    burn = SloBurn()
    assert burn.target_pct == 99.9
    burn.good(999)
    burn.bad(1)  # ratio 0.1% against a 0.1% budget -> 1.0
    assert burn.burn(60.0) == pytest.approx(1.0, rel=0.01)
    # a 100% target must not divide by zero
    b2 = SloBurn(target_pct=100.0)
    b2.bad(1)
    assert math.isfinite(b2.burn(60.0))


def test_sheds_and_deadline_misses_feed_the_burn():
    m = Metrics()
    m.record_batch(8, 0.01)
    m.record_shed(2)
    m.record_deadline_exceeded(1)
    block = m.perf.slo.block()
    assert block["fast"]["good"] == 8
    assert block["fast"]["bad"] == 3
    assert m.snapshot()["slo_burn_rate"]["fast"] > 0.0


# ---------------------------------------------------------------------------
# unit: peak-TFLOPs resolution


def test_peak_tflops_autodetect(monkeypatch):
    monkeypatch.delenv(perf_mod.PEAK_TFLOPS_ENV, raising=False)
    assert peak_tflops_for("TPU v5 lite") == 197.0
    assert peak_tflops_for("TPU v5e") == 197.0
    assert peak_tflops_for("TPU v5p") == 459.0
    assert peak_tflops_for("TPU v4") == 275.0
    assert peak_tflops_for("cpu") == 0.2
    assert peak_tflops_for("weird-new-chip") is None
    assert peak_tflops_for(None) is None
    monkeypatch.setenv(perf_mod.PEAK_TFLOPS_ENV, "123.5")
    assert peak_tflops_for("TPU v5e") == 123.5  # env override wins
    monkeypatch.setenv(perf_mod.PEAK_TFLOPS_ENV, "not-a-number")
    assert peak_tflops_for("TPU v5e") == 197.0  # bad env falls through


# ---------------------------------------------------------------------------
# unit: PerfLedger MFU / duty-cycle math


def _aged_ledger(**kwargs) -> PerfLedger:
    ledger = PerfLedger(**kwargs)
    # age the ledger so the trailing window spans exactly window_s and the
    # rate math is deterministic
    ledger._created = time.monotonic() - 2 * ledger.window_s
    return ledger


def test_mfu_and_duty_cycle_math(monkeypatch):
    monkeypatch.setenv(perf_mod.PEAK_TFLOPS_ENV, "0.000001")  # 1e6 FLOP/s
    ledger = _aged_ledger(window_s=60.0, enabled=True)
    ledger.set_device_info("test-chip", 1)
    assert ledger.peak_tflops == 1e-6
    # 6e6 FLOPs over a 60 s window against 1e6 FLOP/s peak = 10% MFU;
    # half the pixels valid -> useful MFU 5%; 3 s device time -> 5% duty
    ledger.record_dispatch(
        device_s=3.0, batch=4, padded_px=100, valid_px=50, flops=6e6,
        trace_id="t-1", shape="s",
    )
    snap = ledger.snapshot()
    assert snap["mfu_pct"] == pytest.approx(10.0, rel=0.01)
    assert snap["useful_mfu_pct"] == pytest.approx(5.0, rel=0.01)
    assert snap["device_duty_cycle_pct"] == pytest.approx(5.0, rel=0.01)


def test_mfu_zero_when_peak_unknown():
    ledger = _aged_ledger(window_s=60.0, enabled=True)
    ledger.set_device_info("mystery-accelerator", 2)
    ledger.record_dispatch(device_s=1.0, batch=2, flops=1e9)
    snap = ledger.snapshot()
    assert snap["peak_tflops"] is None
    assert snap["mfu_pct"] == 0.0  # never NaN, never a made-up number
    assert snap["device_duty_cycle_pct"] > 0.0  # duty needs no peak


def test_perf_ledger_disabled_is_noop(monkeypatch):
    monkeypatch.setenv(perf_mod.PERF_LEDGER_ENV, "0")
    ledger = PerfLedger()
    assert not ledger.enabled
    ledger.record_dispatch(device_s=1.0, batch=2, flops=1e9)
    snap = ledger.snapshot()
    assert snap["mfu_pct"] == 0.0 and snap["device_duty_cycle_pct"] == 0.0
    assert ledger.top_dispatches() == []


def test_top_dispatches_bounded_and_sorted():
    ledger = _aged_ledger(window_s=60.0, enabled=True, top_k=3)
    for i in range(10):
        ledger.record_dispatch(
            device_s=i / 1000.0, batch=1, trace_id=f"t-{i}", shape="s"
        )
    top = ledger.top_dispatches()
    assert len(top) == 3
    assert [e["trace_id"] for e in top] == ["t-9", "t-8", "t-7"]
    assert top[0]["device_ms"] >= top[1]["device_ms"] >= top[2]["device_ms"]


def test_flops_for_caches_failures():
    ledger = PerfLedger(enabled=True)
    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError("cost analysis broken")

    assert ledger.flops_for("s", boom) is None
    assert ledger.flops_for("s", boom) is None  # cached: no second attempt
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# unit: kernel FLOPs ledger (ISSUE 18 — pallas calls cost 0 under XLA)


def test_note_kernel_flops_collector_scoping():
    from spotter_tpu.obs.perf import collect_kernel_flops, note_kernel_flops

    note_kernel_flops("orphan", 123.0)  # no collector active: dropped
    with collect_kernel_flops() as outer:
        note_kernel_flops("msda_fused", 100.0)
        with collect_kernel_flops() as inner:
            note_kernel_flops("msda_fused", 50.0)
            note_kernel_flops("owl_class_logits", 7.0)
        note_kernel_flops("bad", float("nan"))  # rejected
        note_kernel_flops("bad", -1)  # rejected
        note_kernel_flops("bad", "x")  # rejected
    assert inner == {"msda_fused": 50.0, "owl_class_logits": 7.0, "__total__": 57.0}
    assert outer["msda_fused"] == 150.0 and outer["__total__"] == 157.0
    assert "bad" not in outer and "orphan" not in outer


def test_combine_flops_rules():
    from spotter_tpu.obs.perf import combine_flops

    # cost_analysis empty -> manual total stands alone (None when both empty)
    assert combine_flops(None, None) is None
    assert combine_flops(0, 0.0) is None
    assert combine_flops(None, 5e6) == 5e6
    # ca below the manual total: XLA missed the custom calls -> add
    assert combine_flops(1e6, 5e6) == 6e6
    # ca at/above the manual total: already counted -> trust ca
    assert combine_flops(5e6, 5e6) == 5e6
    assert combine_flops(9e6, 5e6) == 9e6
    # garbage inputs degrade, never raise
    assert combine_flops(float("nan"), 5e6) == 5e6
    assert combine_flops("junk", None) is None
    assert combine_flops(1e6, float("inf")) == 1e6


# ---------------------------------------------------------------------------
# unit: compile ledger


def test_compile_ledger_hits_and_table():
    ledger = CompileLedger(storm_threshold=100)
    assert ledger.record_dispatch("a") is True
    ledger.record_compile("a", 0.5, "warmup")
    assert ledger.record_dispatch("a") is False  # steady state: a hit
    assert ledger.record_dispatch("a") is False
    snap = ledger.snapshot()
    assert snap["compiles_total"] == 1
    assert snap["compile_seconds_total"] == pytest.approx(0.5)
    assert snap["program_cache_hits_total"] == 2
    (entry,) = snap["compile_shapes"]
    assert entry["shape"] == "a" and entry["source"] == "warmup"
    assert entry["count"] == 1


def test_compile_storm_warning(caplog):
    ledger = CompileLedger(storm_threshold=2)
    with caplog.at_level("WARNING", logger="spotter_tpu.obs.perf"):
        for i in range(4):
            ledger.record_dispatch(f"shape-{i}")
            ledger.record_compile(f"shape-{i}", 0.01, "traffic")
    assert any("recompile storm" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# unit: HBM telemetry


class _FakeDevice:
    def __init__(self, dev_id, stats):
        self.id = dev_id
        self._stats = stats

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


def test_hbm_sample_none_safe_and_sums():
    ledger = PerfLedger(enabled=True)
    devices = [
        _FakeDevice(0, None),  # CPU backends return None
        _FakeDevice(1, {"bytes_in_use": 10, "peak_bytes_in_use": 20,
                        "bytes_limit": 100}),
        _FakeDevice(2, {"bytes_in_use": 5, "peak_bytes_in_use": 6,
                        "bytes_limit": 100}),
        _FakeDevice(3, RuntimeError("backend gone")),
    ]
    assert sample_hbm_once(lambda: devices, ledger) == 2
    snap = ledger.snapshot()
    assert snap["hbm_bytes_in_use"] == 15
    assert snap["hbm_peak_bytes"] == 26
    assert snap["hbm_limit_bytes"] == 200
    assert snap["hbm_per_device"]["1"]["bytes_in_use"] == 10
    text = prom.render(snap)
    assert (
        'spotter_tpu_hbm_per_device{device="1",stat="bytes_in_use"} 10'
        in text
    )


def test_hbm_sampler_thread_start_stop():
    ledger = PerfLedger(enabled=True)
    devices = [_FakeDevice(0, {"bytes_in_use": 7, "peak_bytes_in_use": 7,
                               "bytes_limit": 10})]
    sampler = perf_mod.HbmSampler(lambda: devices, ledger, interval_s=0.01)
    assert sampler.start()
    try:
        deadline = time.monotonic() + 2.0
        while (
            ledger.snapshot()["hbm_bytes_in_use"] == 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
    finally:
        sampler.stop()
    assert ledger.snapshot()["hbm_bytes_in_use"] == 7
    disabled = perf_mod.HbmSampler(lambda: devices, ledger, interval_s=0)
    assert not disabled.start()  # interval 0 = off


# ---------------------------------------------------------------------------
# engine integration (tiny models, real jit on CPU)


@pytest.fixture(scope="module")
def rtdetr_engine():
    from spotter_tpu.engine.engine import InferenceEngine
    from spotter_tpu.models import build_detector

    built = build_detector("PekingU/rtdetr_v2_r18vd")
    engine = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2))
    engine.warmup()
    return engine


def test_warmup_fills_compile_ledger_then_steady_state_hits(rtdetr_engine):
    """Acceptance: the ledger counts exactly the warmup programs, and
    steady-state traffic never adds a compile — only cache hits."""
    snap = rtdetr_engine.metrics.snapshot()
    assert snap["compiles_total"] == 2  # one program per bucket
    assert {e["source"] for e in snap["compile_shapes"]} == {"warmup"}
    assert snap["compile_seconds_total"] > 0.0
    hits_before = snap["program_cache_hits_total"]
    img = Image.fromarray(np.full((48, 64, 3), 128, np.uint8))
    for _ in range(3):
        rtdetr_engine.detect([img, img])
    snap = rtdetr_engine.metrics.snapshot()
    assert snap["compiles_total"] == 2  # test-asserted: no recompiles
    assert snap["program_cache_hits_total"] >= hits_before + 3


def test_engine_dispatches_land_in_mfu_ledger(rtdetr_engine):
    img = Image.fromarray(np.full((48, 64, 3), 128, np.uint8))
    rtdetr_engine.detect([img])
    snap = rtdetr_engine.metrics.snapshot()
    assert snap["device_kind"] == "cpu"
    assert snap["peak_tflops"] == 0.2  # the CPU table entry
    assert snap["device_duty_cycle_pct"] > 0.0
    assert snap["mfu_pct"] > 0.0  # cost-analysis FLOPs resolved
    top = rtdetr_engine.metrics.perf.top_dispatches()
    assert top and top[0]["flops"] and top[0]["flops"] > 0


def test_oom_downgrade_shows_up_in_the_ledger(tiny_built_rtdetr):
    """Acceptance: an OOM-downgrade path produces new ledger entries (the
    halves' bucket compiles tagged oom_downgrade)."""
    from spotter_tpu.engine.engine import InferenceEngine

    engine = InferenceEngine(
        tiny_built_rtdetr, threshold=0.0, batch_buckets=(2, 4)
    )
    rng = np.random.default_rng(3)
    images = [
        Image.fromarray(rng.integers(0, 255, (48, 64, 3), dtype=np.uint8))
        for _ in range(4)
    ]
    with faults.inject(engine_oom=1):
        results = engine.detect(images)
    assert len(results) == 4
    snap = engine.metrics.snapshot()
    sources = {e["source"] for e in snap["compile_shapes"]}
    assert "oom_downgrade" in sources
    assert engine.metrics.snapshot()["batch_retries_total"] >= 1


@pytest.fixture(scope="module")
def tiny_built_rtdetr():
    from spotter_tpu.models import build_detector

    return build_detector("PekingU/rtdetr_v2_r18vd")


def test_ragged_canvas_snap_compiles_once():
    """Acceptance: a ragged sub-bucket canvas is ONE new compile-ledger
    entry on first use, then a cache hit — the bounded-compile-count
    invariant (PR 9) as an observable."""
    from spotter_tpu.engine.engine import InferenceEngine
    from spotter_tpu.models import build_detector

    built = build_detector("facebook/detr-resnet-50")
    engine = InferenceEngine(
        built, threshold=0.0, batch_buckets=(2,), device_preprocess=True
    )
    engine.warmup()
    base = engine.metrics.snapshot()["compiles_total"]
    imgs = [
        # (80, 60) resizes to exactly the (64, 48) canvas; the extreme
        # (160, 60) aspect lands at (64, 24) — real padding waste, so the
        # valid/padded split below has something to discount
        Image.fromarray(np.full((80, 60, 3), 90, np.uint8)),
        Image.fromarray(np.full((160, 60, 3), 90, np.uint8)),
    ]
    engine.detect(imgs, canvas_hw=(64, 48))
    snap = engine.metrics.snapshot()
    assert snap["compiles_total"] == base + 1
    assert any(
        e["source"] == "traffic" and "64x48" in e["shape"]
        for e in snap["compile_shapes"]
    )
    hits = snap["program_cache_hits_total"]
    engine.detect(imgs, canvas_hw=(64, 48))  # steady state: no recompile
    snap = engine.metrics.snapshot()
    assert snap["compiles_total"] == base + 1
    assert snap["program_cache_hits_total"] == hits + 1
    # useful MFU discounts padding: the ragged dispatch recorded real pad
    # waste (valid < padded), so the weighted series sits at or below raw
    # MFU (at this tiny scale the rounded gauges may collapse — assert on
    # the per-dispatch record the weighting derives from)
    assert snap["useful_mfu_pct"] <= snap["mfu_pct"]
    ragged_top = [
        e for e in engine.metrics.perf.top_dispatches()
        if e["shape"] and "64x48" in e["shape"]
    ]
    assert ragged_top and all(
        e["valid_px"] < e["padded_px"] for e in ragged_top
    )


# ---------------------------------------------------------------------------
# HTTP surface: /debug/perf + /healthz slo_burn


def _stub_detector() -> AmenitiesDetector:
    engine = StubEngine()
    batcher = MicroBatcher(engine, max_delay_ms=2.0)
    return AmenitiesDetector(engine, batcher, StubHttpClient())


def test_debug_perf_endpoint_admin_gated(monkeypatch):
    monkeypatch.setenv("SPOTTER_TPU_ADMIN_TOKEN", "s3cret")

    async def run():
        detector = _stub_detector()
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post(
                "/detect", json={"image_urls": ["http://example.com/a.jpg"]}
            )
            assert resp.status == 200
            unauth = await client.get("/debug/perf")
            assert unauth.status == 401
            ok = await client.get(
                "/debug/perf", headers={"X-Admin-Token": "s3cret"}
            )
            assert ok.status == 200
            body = await ok.json()
            for key in ("top_dispatches", "compile_shapes", "slo_burn",
                        "mfu_pct", "device_duty_cycle_pct",
                        "hbm_bytes_in_use"):
                assert key in body, key
            assert body["top_dispatches"], "stub dispatch must be recorded"
            assert body["top_dispatches"][0]["device_ms"] >= 0.0
            assert body["slo_burn"]["fast"]["good"] >= 1
            bad_k = await client.get(
                "/debug/perf?k=zap", headers={"X-Admin-Token": "s3cret"}
            )
            assert bad_k.status == 400

    asyncio.run(run())


def test_healthz_reports_slo_burn_block():
    async def run():
        detector = _stub_detector()
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            body = await (await client.get("/healthz")).json()
            burn = body["slo_burn"]
            assert burn["target_pct"] > 0
            assert burn["fast"]["burn_rate"] == 0.0
            assert burn["slow"]["window_s"] == 1800.0

    asyncio.run(run())


def test_metrics_surface_has_perf_gauges_json_and_prom():
    async def run():
        detector = _stub_detector()
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            await client.post(
                "/detect", json={"image_urls": ["http://example.com/a.jpg"]}
            )
            js = await (await client.get("/metrics")).json()
            for key in ("mfu_pct", "useful_mfu_pct",
                        "device_duty_cycle_pct", "compiles_total",
                        "compile_seconds_total", "hbm_bytes_in_use",
                        "slo_burn_rate"):
                assert key in js, key
            assert js["device_duty_cycle_pct"] >= 0.0
            text = await (
                await client.get("/metrics?format=prometheus")
            ).text()
            assert "spotter_tpu_mfu_pct" in text
            assert 'spotter_tpu_slo_burn_rate{window="fast"}' in text
            assert "# TYPE spotter_tpu_compiles_total counter" in text

    asyncio.run(run())
