"""Param-cache round-trip: the runtime load path must work with ONLY the
cache (no torch/transformers), since the serving image uninstalls them after
baking (Dockerfile)."""

import pytest

import numpy as np

from spotter_tpu.convert import loader
from spotter_tpu.models.configs import DetrConfig, RTDetrConfig


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def test_config_json_round_trip():
    import dataclasses
    import json

    cfg = RTDetrConfig(id2label=((0, "tv"), (1, "couch")))
    data = json.loads(json.dumps(dataclasses.asdict(cfg)))
    back = loader.config_from_dict(RTDetrConfig, data)
    assert back == cfg
    assert hash(back) == hash(cfg)  # still a static-arg-compatible dataclass

    dcfg = DetrConfig(id2label=((3, "car"),))
    data = json.loads(json.dumps(dataclasses.asdict(dcfg)))
    assert loader.config_from_dict(DetrConfig, data) == dcfg


def test_cache_round_trip_without_transformers(tmp_path, monkeypatch):
    monkeypatch.setenv(loader.CACHE_ENV, str(tmp_path))
    cfg = DetrConfig(num_labels=5, id2label=((0, "tv"),))
    params = {"backbone": {"stem0": {"conv": {"kernel": np.ones((3, 3, 3, 8), np.float32)}}}}
    path = loader._cache_path("fake/model")
    loader._save_cache(path, cfg, params)

    got = loader._load_cache(path, DetrConfig)
    assert got is not None
    got_cfg, got_params = got
    assert got_cfg == cfg
    np.testing.assert_array_equal(
        got_params["backbone"]["stem0"]["conv"]["kernel"],
        params["backbone"]["stem0"]["conv"]["kernel"],
    )


def test_incomplete_cache_is_miss(tmp_path, monkeypatch):
    monkeypatch.setenv(loader.CACHE_ENV, str(tmp_path))
    path = loader._cache_path("fake/partial")
    (path / "params").mkdir(parents=True)  # params dir without config.json
    assert loader._load_cache(path, DetrConfig) is None
