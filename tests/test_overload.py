"""Overload-control tier tests (ISSUE 8): the AIMD limiter state machine,
class-ordered shedding (bulk strictly before slo), brownout rung hysteresis
(no flapping across the arm/disarm boundary), the serve-stale cache path,
jittered Retry-After hints, deadline-aware fetch attempts, and the
opt-in contract (SPOTTER_TPU_ADMIT_* unset keeps the static queue-depth
semantics). The state machines are pure units — fake clock, scripted
saturation, no engine; the integration half drives the real MicroBatcher
over the stub engine and the standalone HTTP surface."""

import asyncio
import os
import random
import time

import httpx
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

os.environ.setdefault("SPOTTER_TPU_TINY", "1")

from spotter_tpu.caching.result_cache import ResultCache
from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.serving.detector import AmenitiesDetector, FetchError
from spotter_tpu.serving.overload import (
    ADMIT_EDGE_TARGET_ENV,
    ADMIT_TARGET_ENV,
    BULK,
    SLO,
    AdaptiveLimiter,
    AdmitLimitError,
    BrownoutController,
    BrownoutShedError,
    build_overload_control,
    edge_limiter_from_env,
)
from spotter_tpu.serving.resilience import (
    BACKOFF_JITTER_ENV,
    Deadline,
    DeadlineExceededError,
    QueueFullError,
    jittered_retry_after,
)
from spotter_tpu.serving.standalone import make_app
from spotter_tpu.testing import faults
from spotter_tpu.testing.stub_engine import StubEngine, StubHttpClient


class FakeClock:
    def __init__(self, now: float = 1000.0) -> None:
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def _img():
    return Image.fromarray(np.zeros((8, 8, 3), np.uint8))


# ---------------------------------------------------------------- limiter


def test_aimd_decrease_increase_floor_ceiling():
    clock = FakeClock()
    lim = AdaptiveLimiter(
        target_ms=50.0, floor=2, ceiling=10, increase=1.0, decrease=0.5,
        interval_s=1.0, clock=clock,
    )
    assert lim.limit == 10  # starts at the ceiling (optimistic)

    # over target -> multiplicative decrease
    clock.advance(1.1)
    lim.observe(200.0)
    assert lim.limit == 5
    clock.advance(1.1)
    lim.observe(200.0)
    assert lim.limit == 2  # int(2.5)
    # floor clamp + pinned signal
    clock.advance(1.1)
    lim.observe(200.0)
    clock.advance(1.1)
    lim.observe(200.0)
    assert lim.limit == 2
    assert lim.pinned_at_floor()

    # under target -> additive increase, one step per interval
    clock.advance(1.1)
    lim.observe(5.0)
    assert not lim.pinned_at_floor()
    for _ in range(20):
        clock.advance(1.1)
        lim.observe(5.0)
    assert lim.limit == 10  # ceiling clamp


def test_aimd_idle_tick_recovers_and_signal_decays():
    clock = FakeClock()
    lim = AdaptiveLimiter(
        target_ms=50.0, floor=1, ceiling=8, increase=1.0, decrease=0.5,
        interval_s=1.0, clock=clock,
    )
    for _ in range(6):
        clock.advance(1.1)
        lim.observe(500.0)
    assert lim.pinned_at_floor() and lim.last_p90_ms == 500.0
    # zero traffic: idle ticks probe the limit back up and decay the p90 —
    # without this a floor-pinned limiter could never disarm the brownout
    clock.advance(1.1)
    lim.tick()
    assert lim.last_p90_ms == 0.0
    for _ in range(10):
        clock.advance(1.1)
        lim.tick()
    assert lim.limit == 8 and not lim.pinned_at_floor()


def test_aimd_update_rate_is_interval_bound():
    clock = FakeClock()
    lim = AdaptiveLimiter(
        target_ms=50.0, floor=1, ceiling=8, decrease=0.5, interval_s=1.0,
        clock=clock,
    )
    # many over-target samples inside ONE interval -> at most one decrease
    clock.advance(1.1)
    for _ in range(50):
        lim.observe(500.0)
    assert lim.limit == 4


def test_class_order_bulk_sheds_strictly_before_slo():
    clock = FakeClock()
    lim = AdaptiveLimiter(
        target_ms=50.0, floor=1, ceiling=2, interval_s=1e9, clock=clock,
    )
    b = lim.try_admit(BULK)
    s = lim.try_admit(SLO)
    assert b is not None and s is not None
    # at the same instant, over the limit: bulk sheds, slo does not (it
    # rides the bounded soft overage while bulk holds a slot)
    assert lim.try_admit(BULK) is None
    s2 = lim.try_admit(SLO)
    assert s2 is not None
    assert lim.sheds_total[BULK] == 1 and lim.sheds_total[SLO] == 0
    for adm in (b, s, s2):
        adm.release()
    # slo alone at the limit DOES shed — the overage is bulk-backed only
    a1, a2 = lim.try_admit(SLO), lim.try_admit(SLO)
    assert a1 is not None and a2 is not None
    assert lim.try_admit(SLO) is None
    assert lim.sheds_total[SLO] == 1


def test_revocation_newest_bulk_first():
    clock = FakeClock()
    lim = AdaptiveLimiter(
        target_ms=50.0, floor=1, ceiling=3, interval_s=1e9, clock=clock,
    )
    revoked = []
    admissions = {}
    for name in ("b1", "b2", "b3"):
        adm = lim.try_admit(BULK)
        adm.attach_revoke(lambda n=name: revoked.append(n))
        admissions[name] = adm
    # full; an slo arrival revokes the NEWEST queued bulk (LIFO-ish)
    s1 = lim.try_admit(SLO)
    assert s1 is not None and revoked == ["b3"]
    assert lim.in_flight == 3  # the revoked slot was reused, not leaked
    # dispatched work leaves the stack: b2 becomes unrevocable, so the next
    # revocation takes b1 (the only queued bulk left)
    admissions["b2"].make_unrevocable()
    s2 = lim.try_admit(SLO)
    assert s2 is not None and revoked == ["b3", "b1"]
    # nothing revocable left, but bulk (b2) still holds a slot -> soft admit
    s3 = lim.try_admit(SLO)
    assert s3 is not None and revoked == ["b3", "b1"]
    assert lim.revoked_total == 2


def test_release_is_idempotent():
    lim = AdaptiveLimiter(target_ms=50.0, floor=1, ceiling=4, interval_s=1e9)
    adm = lim.try_admit(BULK)
    adm.release()
    adm.release()
    assert lim.in_flight == 0


def test_limiter_from_env_opt_in(monkeypatch):
    monkeypatch.delenv(ADMIT_TARGET_ENV, raising=False)
    assert AdaptiveLimiter.from_env() is None
    assert build_overload_control() == (None, None)
    monkeypatch.setenv(ADMIT_TARGET_ENV, "0")
    assert AdaptiveLimiter.from_env() is None
    monkeypatch.setenv(ADMIT_TARGET_ENV, "25")
    lim = AdaptiveLimiter.from_env()
    assert lim is not None and lim.target_ms == 25.0
    limiter, brownout = build_overload_control()
    assert limiter is not None and brownout is not None
    # the edge knob is independent
    monkeypatch.delenv(ADMIT_EDGE_TARGET_ENV, raising=False)
    assert edge_limiter_from_env() is None
    monkeypatch.setenv(ADMIT_EDGE_TARGET_ENV, "100")
    assert edge_limiter_from_env().target_ms == 100.0


def test_overload_spike_fault_cuts_limit_without_traffic():
    clock = FakeClock()
    lim = AdaptiveLimiter(
        target_ms=50.0, floor=1, ceiling=8, decrease=0.5, interval_s=1.0,
        clock=clock,
    )
    with faults.inject(overload_spike=2):
        clock.advance(1.1)
        lim.tick()
        assert lim.limit == 4 and lim.last_p90_ms == 500.0
        clock.advance(1.1)
        lim.tick()
        assert lim.limit == 2
        # spike exhausted: the next idle tick recovers (default additive
        # increase is 2.0)
        clock.advance(1.1)
        lim.tick()
        assert lim.limit == 4


# --------------------------------------------------------------- brownout


def _stepped_brownout(clock, sat, **kwargs):
    kwargs.setdefault("arm_s", 1.0)
    kwargs.setdefault("disarm_s", 2.0)
    return BrownoutController(lambda: sat["v"], clock=clock, **kwargs)


def test_brownout_rungs_arm_one_at_a_time_and_disarm_with_hysteresis():
    clock = FakeClock()
    sat = {"v": False}
    bc = _stepped_brownout(clock, sat)
    assert bc.evaluate() == 0

    sat["v"] = True
    assert bc.evaluate() == 0  # saturation must SUSTAIN for arm_s
    clock.advance(0.5)
    assert bc.evaluate() == 0
    clock.advance(0.6)
    assert bc.evaluate() == 1
    assert bc.evaluate() == 1  # no double-step within the arm window
    clock.advance(1.1)
    assert bc.evaluate() == 2
    clock.advance(1.1)
    assert bc.evaluate() == 3
    clock.advance(1.1)
    assert bc.evaluate() == 4
    clock.advance(5.0)
    assert bc.evaluate() == 4  # max rung, stays

    # clear must SUSTAIN for disarm_s (2x arm here); the clear window
    # starts at the first evaluate() that SEES the clear signal
    sat["v"] = False
    assert bc.evaluate() == 4
    clock.advance(1.9)
    assert bc.evaluate() == 4
    clock.advance(0.2)
    assert bc.evaluate() == 3
    for expected in (2, 1, 0):
        clock.advance(2.1)
        assert bc.evaluate() == expected
    clock.advance(10.0)
    assert bc.evaluate() == 0


def test_brownout_no_flap_across_boundary():
    clock = FakeClock()
    sat = {"v": True}
    bc = _stepped_brownout(clock, sat)
    bc.evaluate()  # prime: the saturation window starts when first seen
    clock.advance(1.1)
    bc.evaluate()
    clock.advance(1.1)
    assert bc.evaluate() == 2
    # a signal oscillating FASTER than both windows moves nothing: every
    # toggle resets the opposite window before it can complete
    for _ in range(20):
        sat["v"] = not sat["v"]
        clock.advance(0.4)
        assert bc.evaluate() == 2


def test_brownout_transitions_pin_recorder_traces_and_gauge():
    from spotter_tpu.engine.metrics import Metrics
    from spotter_tpu.obs import FlightRecorder

    clock = FakeClock()
    sat = {"v": True}
    metrics = Metrics()
    recorder = FlightRecorder(ring=8, slowest_k=0)
    bc = BrownoutController(
        lambda: sat["v"], arm_s=1.0, disarm_s=2.0, clock=clock,
        metrics=metrics, recorder=recorder,
    )
    bc.evaluate()  # prime the saturation window
    clock.advance(1.1)
    bc.evaluate()
    clock.advance(1.1)
    bc.evaluate()
    snap = metrics.snapshot()
    assert snap["brownout_rung"] == 2
    assert snap["brownout_transitions_total"] == 2
    rec = recorder.snapshot()
    assert rec["errors_total"] == 2
    assert all(t["status"] == "brownout" for t in rec["errors"])
    assert "rung 1" in rec["errors"][-1]["error"]


def test_brownout_rung_effects():
    clock = FakeClock()
    sat = {"v": True}
    bc = _stepped_brownout(clock, sat, threshold_boost=0.2)
    bc.evaluate()  # prime the saturation window
    rung_effects = []
    for _ in range(4):
        clock.advance(1.1)
        bc.evaluate()
        rung_effects.append(
            (bc.stale_ok(), bc.bucket_cap_active(),
             bc.threshold_boost_value(), bc.shed_bulk())
        )
    assert rung_effects == [
        (True, False, 0.0, False),
        (True, True, 0.0, False),
        (True, True, 0.2, False),
        (True, True, 0.2, True),
    ]
    assert bc.markers() == ["bucket_cap", "threshold"]


def test_brownout_hold_blocks_deescalation_but_never_escalates():
    clock = FakeClock()
    sat = {"v": True}
    holding = {"v": False}
    bc = BrownoutController(
        lambda: sat["v"], arm_s=1.0, disarm_s=2.0, clock=clock,
        hold=lambda: holding["v"],
    )
    bc.evaluate()
    for _ in range(2):
        clock.advance(1.1)
        bc.evaluate()
    assert bc.rung == 2
    # not saturated but still shedding: the rung HOLDS (no exit, no entry)
    sat["v"] = False
    holding["v"] = True
    for _ in range(10):
        clock.advance(2.5)
        assert bc.evaluate() == 2
    # shedding stops: the clear window finally runs and the ladder exits
    holding["v"] = False
    bc.evaluate()  # clear window starts when first seen
    clock.advance(2.1)
    assert bc.evaluate() == 1
    clock.advance(2.1)
    assert bc.evaluate() == 0
    # hold never escalates a calm system
    holding["v"] = True
    for _ in range(5):
        clock.advance(2.5)
        assert bc.evaluate() == 0


def test_saturation_signals_shed_delta_holds():
    from spotter_tpu.engine.metrics import Metrics
    from spotter_tpu.serving.overload import saturation_signals

    metrics = Metrics()
    lim = AdaptiveLimiter(
        target_ms=50.0, floor=1, ceiling=8, interval_s=1e9, metrics=metrics
    )
    saturated, hold = saturation_signals(lim, 400.0, metrics=metrics)
    assert saturated() is False and hold() is False
    metrics.record_admit_shed(BULK)
    assert hold() is True  # new sheds since last poll
    assert hold() is False  # delta consumed; calm until the next shed


# ----------------------------------------------------- jittered Retry-After


def test_jittered_retry_after_band_and_seed(monkeypatch):
    monkeypatch.delenv(BACKOFF_JITTER_ENV, raising=False)  # default on
    rng = random.Random(42)
    vals = [jittered_retry_after(10.0, rng=rng) for _ in range(200)]
    assert all(7.5 <= v <= 12.5 for v in vals)  # +-25% full jitter
    assert len({round(v, 6) for v in vals}) > 100  # actually spread
    # seeded determinism: same seed, same draw
    assert jittered_retry_after(10.0, rng=random.Random(7)) == pytest.approx(
        jittered_retry_after(10.0, rng=random.Random(7))
    )
    # knob off -> exact value
    monkeypatch.setenv(BACKOFF_JITTER_ENV, "0")
    assert jittered_retry_after(10.0) == 10.0
    assert jittered_retry_after(10.0, enabled=False) == 10.0


# --------------------------------------------------------- stale-serve path


def test_result_cache_stale_entry_served_only_when_allowed():
    clock = FakeClock()
    rc = ResultCache(max_bytes=1 << 20, ttl_s=10.0, clock=clock)
    rc.put("k", [{"label": "tv", "score": 0.9, "box": [1, 2, 3, 4]}])
    fresh, stale = rc.get_entry("k")
    assert fresh and stale is False
    clock.advance(11.0)
    # brownout rung 1: the expired entry is acceptable AND kept
    value, stale = rc.get_entry("k", stale_ok=True)
    assert value and stale is True
    value, stale = rc.get_entry("k", stale_ok=True)
    assert value and stale is True
    # fresh path: expired entry drops and misses, exactly as before
    assert rc.get_entry("k") == (None, False)
    assert rc.get_entry("k", stale_ok=True) == (None, False)


# ----------------------------------------------- batcher integration (async)


def test_batcher_static_semantics_preserved_without_admit_env(monkeypatch):
    """Acceptance: SPOTTER_TPU_ADMIT_* unset -> no limiter, no brownout,
    bounded queue with the exact static QueueFullError shed."""
    monkeypatch.delenv(ADMIT_TARGET_ENV, raising=False)

    async def run():
        eng = StubEngine(service_ms=50.0)
        b = MicroBatcher(
            eng, max_batch=1, max_delay_ms=1.0, max_in_flight=1, max_queue=2
        )
        assert b.limiter is None and b.brownout is None
        assert b._queue.maxsize == 2
        img = _img()
        tasks = [asyncio.create_task(b.submit(img)) for _ in range(6)]
        results = await asyncio.gather(*tasks, return_exceptions=True)
        shed = [r for r in results if isinstance(r, QueueFullError)]
        ok = [r for r in results if isinstance(r, list)]
        assert shed and ok  # bounded queue shed some, served the rest
        await b.stop()

    asyncio.run(run())


def test_batcher_limiter_revokes_queued_bulk_for_slo():
    async def run():
        eng = StubEngine(service_ms=80.0)
        lim = AdaptiveLimiter(
            target_ms=10_000.0, floor=1, ceiling=2, interval_s=1e9
        )
        b = MicroBatcher(
            eng, max_batch=1, max_delay_ms=1.0, max_in_flight=1,
            limiter=lim, brownout=None,
        )
        img = _img()
        t_b1 = asyncio.create_task(b.submit(img, cls=BULK))
        await asyncio.sleep(0.03)  # b1 dispatched (unrevocable)
        t_b2 = asyncio.create_task(b.submit(img, cls=BULK))
        await asyncio.sleep(0.01)  # b2 queued, revocable
        # the limit (2) is fully held; the slo arrival revokes b2
        slo_result = await b.submit(img, cls=SLO)
        assert slo_result
        with pytest.raises(AdmitLimitError):
            await t_b2
        assert await t_b1  # the dispatched bulk still completes
        assert lim.revoked_total == 1
        # queue_wait joined the stage histograms (the control signal is
        # observable in /metrics)
        assert "stage_queue_wait_ms_p90" in eng.metrics.snapshot()
        await b.stop()

    asyncio.run(run())


def test_batcher_limiter_sheds_bulk_when_full():
    async def run():
        eng = StubEngine(service_ms=60.0)
        lim = AdaptiveLimiter(
            target_ms=10_000.0, floor=1, ceiling=1, interval_s=1e9,
            metrics=eng.metrics,
        )
        b = MicroBatcher(
            eng, max_batch=1, max_delay_ms=1.0, max_in_flight=1,
            limiter=lim, brownout=None,
        )
        img = _img()
        t1 = asyncio.create_task(b.submit(img, cls=BULK))
        await asyncio.sleep(0.02)
        with pytest.raises(AdmitLimitError) as ei:
            await b.submit(img, cls=BULK)
        assert ei.value.status == 429 and ei.value.retry_after_s > 0
        assert await t1
        assert eng.metrics.snapshot()["admit_sheds_total"]["bulk"] == 1
        await b.stop()

    asyncio.run(run())


def test_batcher_brownout_bulk_503_and_bucket_cap():
    async def run():
        eng = StubEngine(service_ms=1.0)  # buckets (1, 2, 4, 8)
        clock = FakeClock()
        sat = {"v": True}
        bc = BrownoutController(
            lambda: sat["v"], arm_s=1.0, disarm_s=100.0, clock=clock,
            metrics=eng.metrics,
        )
        b = MicroBatcher(
            eng, max_delay_ms=1.0, limiter=None, brownout=bc
        )
        assert b._dispatch_bucket() == 8
        bc.evaluate()  # prime the saturation window
        clock.advance(1.1)
        bc.evaluate()
        clock.advance(1.1)
        bc.evaluate()  # rung 2: bucket cap
        assert b._dispatch_bucket() == 4
        img = _img()
        assert await b.submit(img, cls=BULK)  # rung 2 serves bulk fine
        clock.advance(1.1)
        bc.evaluate()
        clock.advance(1.1)
        bc.evaluate()  # rung 4: bulk-only 503
        with pytest.raises(BrownoutShedError) as ei:
            await b.submit(img, cls=BULK)
        assert ei.value.status == 503
        assert await b.submit(img, cls=SLO)  # slo keeps serving
        await b.stop()

    asyncio.run(run())


# ------------------------------------------------ detector + HTTP surface


def test_detector_serves_stale_with_degraded_marker():
    async def run():
        eng = StubEngine(service_ms=1.0)
        cache_clock = FakeClock()
        rc = ResultCache(
            max_bytes=1 << 20, ttl_s=5.0, clock=cache_clock,
            metrics=eng.metrics,
        )
        clock = FakeClock()
        sat = {"v": True}
        bc = BrownoutController(
            lambda: sat["v"], arm_s=1.0, disarm_s=100.0, clock=clock,
            metrics=eng.metrics,
        )
        b = MicroBatcher(eng, max_delay_ms=1.0, limiter=None, brownout=bc)
        det = AmenitiesDetector(eng, b, StubHttpClient(), cache=rc)
        payload = {"image_urls": ["http://example.com/room.jpg"]}
        resp1 = await det.detect(payload)
        assert resp1.degraded is None  # fresh fill, no brownout shaping
        batches_after_fill = eng.metrics.snapshot()["batches_total"]
        cache_clock.advance(10.0)  # entry expires
        bc.evaluate()  # prime the saturation window
        clock.advance(1.1)
        bc.evaluate()  # rung 1: serve-stale
        resp2 = await det.detect(payload)
        assert resp2.degraded == ["stale"]
        assert resp2.images[0].detections  # real content, just stale
        snap = eng.metrics.snapshot()
        assert snap["batches_total"] == batches_after_fill  # no engine pass
        assert snap["stale_served_total"] == 1
        await det.aclose()

    asyncio.run(run())


def test_standalone_brownout_surface():
    """/healthz status=brownout + rung, /metrics brownout_rung, the
    degraded marker on the wire, and the bulk-only 503 — end to end over
    the real HTTP surface with X-Request-Class."""

    async def run():
        eng = StubEngine(service_ms=1.0)
        clock = FakeClock()
        sat = {"v": True}
        bc = BrownoutController(
            lambda: sat["v"], arm_s=1.0, disarm_s=100.0, clock=clock,
            metrics=eng.metrics,
        )
        b = MicroBatcher(eng, max_delay_ms=1.0, limiter=None, brownout=bc)
        det = AmenitiesDetector(eng, b, StubHttpClient(), cache=None)
        bc.evaluate()  # prime the saturation window
        for _ in range(2):  # step to rung 2 (bucket_cap)
            clock.advance(1.1)
            bc.evaluate()
        app = make_app(detector=det)
        async with TestClient(TestServer(app)) as client:
            h = await client.get("/healthz")
            assert h.status == 200
            body = await h.json()
            assert body["status"] == "brownout"
            assert body["brownout"]["rung"] == 2
            assert body["admit"] == {"enabled": False}

            m = await (await client.get("/metrics")).json()
            assert m["brownout_rung"] == 2
            assert m["brownout_transitions_total"] == 2

            prom_text = await (
                await client.get("/metrics?format=prometheus")
            ).text()
            assert "spotter_tpu_brownout_rung 2" in prom_text

            r = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
            )
            assert r.status == 200
            rbody = await r.json()
            assert rbody["degraded"] == ["bucket_cap"]

            for _ in range(2):  # step to rung 4 (bulk-only 503)
                clock.advance(1.1)
                bc.evaluate()
            shed = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
                headers={"X-Request-Class": "bulk"},
            )
            assert shed.status == 503
            assert "Retry-After" in shed.headers
            ok = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
                headers={"X-Request-Class": "slo"},
            )
            assert ok.status == 200

    asyncio.run(run())


# ------------------------------------------- deadline-aware fetch attempts


class _SlowConnectClient:
    """Every GET hangs `delay_s` then fails with a retryable error."""

    def __init__(self, delay_s: float) -> None:
        self.delay_s = delay_s
        self.attempts = 0

    async def get(self, url: str):
        self.attempts += 1
        await asyncio.sleep(self.delay_s)
        raise httpx.ConnectError(f"injected connect failure for {url}")

    async def aclose(self) -> None:
        pass


class _InstantFailClient(_SlowConnectClient):
    def __init__(self) -> None:
        super().__init__(0.0)

    async def get(self, url: str):
        self.attempts += 1
        raise httpx.ConnectError(f"injected connect failure for {url}")


def test_fetch_attempt_timeout_clamped_to_deadline():
    async def run():
        eng = StubEngine()
        client = _SlowConnectClient(delay_s=5.0)
        det = AmenitiesDetector(
            eng, MicroBatcher(eng, max_delay_ms=1.0), client, cache=None
        )
        deadline = Deadline.after(0.25)
        t0 = time.monotonic()
        with pytest.raises((DeadlineExceededError, FetchError)):
            await det._fetch_with_retries("http://x/a.jpg", deadline)
        elapsed = time.monotonic() - t0
        # a 5 s hang against a 250 ms budget must die in ~one budget, not
        # 3 attempts x 5 s + 8 s of backoff
        assert elapsed < 1.5
        assert client.attempts == 1
        await det.batcher.stop()

    asyncio.run(run())


def test_fetch_retries_skipped_when_budget_cannot_cover_backoff():
    async def run():
        eng = StubEngine()
        client = _InstantFailClient()
        det = AmenitiesDetector(
            eng, MicroBatcher(eng, max_delay_ms=1.0), client, cache=None
        )
        deadline = Deadline.after(1.0)  # backoff min is 4 s > budget
        t0 = time.monotonic()
        with pytest.raises(httpx.ConnectError):
            await det._fetch_with_retries("http://x/a.jpg", deadline)
        elapsed = time.monotonic() - t0
        assert elapsed < 0.5  # no pointless 4 s sleep
        assert client.attempts == 1  # the remaining attempts were skipped
        await det.batcher.stop()

    asyncio.run(run())


def test_fetch_deadline_none_keeps_reference_retry_policy(monkeypatch):
    """Without a deadline the 3-attempt contract is untouched (backoff is
    patched to zero so the test doesn't sleep 8 s)."""
    from spotter_tpu.serving import detector as detector_mod

    monkeypatch.setattr(detector_mod, "FETCH_RETRY_WAIT_MIN_S", 0.0)
    monkeypatch.setattr(detector_mod, "FETCH_RETRY_WAIT_MAX_S", 0.0)

    async def run():
        eng = StubEngine()
        client = _InstantFailClient()
        det = AmenitiesDetector(
            eng, MicroBatcher(eng, max_delay_ms=1.0), client, cache=None
        )
        with pytest.raises(httpx.ConnectError):
            await det._fetch_with_retries("http://x/a.jpg")
        assert client.attempts == 3
        await det.batcher.stop()

    asyncio.run(run())
