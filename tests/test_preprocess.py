import numpy as np
from PIL import Image

from spotter_tpu.ops.preprocess import (
    DETR_SPEC,
    RTDETR_SPEC,
    PreprocessSpec,
    batch_images,
    preprocess_image,
    shortest_edge_size,
)


def _img(h, w, seed=0):
    rng = np.random.default_rng(seed)
    return Image.fromarray(rng.integers(0, 255, size=(h, w, 3), dtype=np.uint8))


def test_fixed_spec_shape_and_range():
    arr, mask, orig = preprocess_image(_img(480, 640), RTDETR_SPEC)
    assert arr.shape == (640, 640, 3)
    assert orig == (480, 640)
    assert mask.all()
    assert 0.0 <= arr.min() and arr.max() <= 1.0  # rescale only, no normalize


def test_shortest_edge_size_caps_long_side():
    # 1066, not round()'s 1067: the HF DETR processor truncates the derived
    # long side (int(800*640/480)), and golden parity follows its arithmetic
    # exactly (tests/test_preprocess_hf_parity.py).
    assert shortest_edge_size((480, 640), 800, 1333) == (800, 1066)
    # long side would exceed the cap -> scale by the long side instead
    assert shortest_edge_size((500, 2000), 800, 1333) == (333, 1333)


def test_shortest_edge_size_boundary_cases_fit_bucket():
    # HF's equality branch keeps original dims even ONE pixel over the cap
    # (666x1334 stays 1334 wide); the static bucket clamps that pixel.
    assert shortest_edge_size((666, 1334), 800, 1333) == (666, 1333)
    assert shortest_edge_size((1334, 666), 800, 1333) == (1333, 666)
    # extreme aspect ratio: never emit a 0-sized edge
    h, w = shortest_edge_size((1, 3000), 800, 1333)
    assert h >= 1 and w >= 1 and max(h, w) <= 1333
    # and the full preprocess must fit its static bucket on those images
    arr, _, _ = preprocess_image(_img(666, 1334), DETR_SPEC)
    assert arr.shape == (*DETR_SPEC.input_hw, 3)


def test_detr_spec_landscape_and_portrait_fit_bucket():
    for h, w in [(480, 640), (1000, 500), (640, 480), (2000, 500)]:
        arr, mask, _ = preprocess_image(_img(h, w), DETR_SPEC)
        assert arr.shape == (*DETR_SPEC.input_hw, 3)
        # mask marks the valid region only
        assert 0 < mask.sum() <= mask.size


def test_normalization_applies_mean_std():
    spec = PreprocessSpec(mode="fixed", size=(32, 32), mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5))
    arr, _, _ = preprocess_image(_img(32, 32), spec)
    assert arr.min() >= -1.0 - 1e-6 and arr.max() <= 1.0 + 1e-6


def test_batch_images_stacks_and_sizes():
    pixels, masks, sizes = batch_images([_img(480, 640), _img(100, 200, 1)], RTDETR_SPEC)
    assert pixels.shape == (2, 640, 640, 3)
    assert masks.shape == (2, 640, 640)
    np.testing.assert_array_equal(sizes, [[480, 640], [100, 200]])


def test_decode_bomb_guard_blocks_oversized_images(monkeypatch):
    """SPOTTER_TPU_MAX_IMAGE_PIXELS (ISSUE 4 satellite): both DecodePool
    preprocess paths reject an over-cap image before any resize; <=0
    disables the guard."""
    import pytest

    from spotter_tpu.ops.preprocess import (
        ImageTooLargeError,
        decode_resize_uint8,
    )

    img = Image.fromarray(np.zeros((40, 50, 3), np.uint8))  # 2000 px
    monkeypatch.setenv("SPOTTER_TPU_MAX_IMAGE_PIXELS", "1999")
    with pytest.raises(ImageTooLargeError, match="decode-bomb guard"):
        preprocess_image(img, RTDETR_SPEC)
    with pytest.raises(ImageTooLargeError, match="decode-bomb guard"):
        decode_resize_uint8(img, RTDETR_SPEC)
    monkeypatch.setenv("SPOTTER_TPU_MAX_IMAGE_PIXELS", "2000")
    pixels, _, _ = preprocess_image(img, RTDETR_SPEC)
    assert pixels.shape == (640, 640, 3)
    monkeypatch.setenv("SPOTTER_TPU_MAX_IMAGE_PIXELS", "0")  # disabled
    preprocess_image(img, RTDETR_SPEC)
