"""Ray Serve adapter tests (VERDICT r2 missing #2).

`spotter_tpu.serving.app.ray_deployment` is the manifest's import_path target
(rayservice-tpu-template.yaml) — the production entry the reference exercises
by actually running Ray (serve.py:64, 205). Ray is not installed in this
image, so these tests install a minimal fake `ray`/`ray.serve` +
`starlette.requests` into sys.modules and reimport the module — the same
fake-fabric trick the manager tests use for the k8s apiserver
(manager/tests/manager_test.cpp). This executes the adapter end to end:
module import builds `deployment`, the deployment class constructs via
`build_detector_app`, and `__call__` routes the parsed JSON body to
`AmenitiesDetector.detect`.
"""

import asyncio
import importlib
import sys
import types

import pytest

APP_MODULE = "spotter_tpu.serving.app"
FAKE_MODULE_NAMES = ("ray", "ray.serve", "starlette", "starlette.requests")


class FakeBound:
    """What `serve.deployment(...).bind(args)` returns: the deferred graph
    node Ray would instantiate at deploy time (cls + ctor args, no init)."""

    def __init__(self, cls, args, kwargs):
        self.cls = cls
        self.args = args
        self.kwargs = kwargs


class FakeDeployment:
    def __init__(self, cls):
        self.func_or_class = cls

    def bind(self, *args, **kwargs):
        return FakeBound(self.func_or_class, args, kwargs)


def _make_fake_modules():
    ray = types.ModuleType("ray")
    serve = types.ModuleType("ray.serve")

    def deployment(cls=None, **_opts):
        if cls is None:  # used as @serve.deployment(...) with options
            return FakeDeployment
        return FakeDeployment(cls)

    serve.deployment = deployment
    ray.serve = serve

    starlette = types.ModuleType("starlette")
    requests_mod = types.ModuleType("starlette.requests")

    class Request:  # only referenced as a type annotation in the adapter
        pass

    requests_mod.Request = Request
    starlette.requests = requests_mod
    return {
        "ray": ray,
        "ray.serve": serve,
        "starlette": starlette,
        "starlette.requests": requests_mod,
    }


def _reimport_app_with_fakes(fakes):
    saved = {n: sys.modules.pop(n, None) for n in FAKE_MODULE_NAMES}
    sys.modules.update(fakes)
    try:
        return importlib.reload(importlib.import_module(APP_MODULE)), saved
    except Exception:
        _restore_modules(saved)
        raise


def _restore_modules(saved):
    for name in FAKE_MODULE_NAMES:
        sys.modules.pop(name, None)
    for name, mod in saved.items():
        if mod is not None:
            sys.modules[name] = mod


@pytest.fixture
def app_with_fake_ray(monkeypatch):
    """Reimport serving.app with fake Ray present and MODEL_NAME set.

    bind() defers construction (like real Ray), so no model is loaded here.
    Teardown reimports the module with the fakes removed so other tests see
    the standalone-mode module (`deployment is None`) again.
    """
    monkeypatch.setenv("MODEL_NAME", "rtdetr_v2_r18vd")
    fakes = _make_fake_modules()
    mod, saved = _reimport_app_with_fakes(fakes)
    try:
        yield mod
    finally:
        _restore_modules(saved)
        importlib.reload(importlib.import_module(APP_MODULE))


def test_import_with_ray_builds_bound_deployment(app_with_fake_ray):
    mod = app_with_fake_ray
    assert isinstance(mod.deployment, FakeBound)
    # the bound ctor arg is the MODEL_NAME the manifest sets (serve.py:205)
    assert mod.deployment.args == ("rtdetr_v2_r18vd",)
    assert mod.deployment.cls.__name__ == "RayAmenitiesDetector"


def test_deployment_call_routes_to_detect(app_with_fake_ray, monkeypatch):
    mod = app_with_fake_ray

    sentinel_response = object()
    seen = {}

    class FakeInner:
        async def detect(self, payload):
            seen["payload"] = payload
            return sentinel_response

    def fake_build(model_name, **kwargs):
        seen["model_name"] = model_name
        seen["build_kwargs"] = kwargs
        return FakeInner()

    # the closure resolves build_detector_app from the module at call time
    monkeypatch.setattr(mod, "build_detector_app", fake_build)

    inner_cls = mod.deployment.cls
    instance = inner_cls(*mod.deployment.args)
    assert seen["model_name"] == "rtdetr_v2_r18vd"
    # production replicas warm every bucket before taking traffic
    assert seen["build_kwargs"].get("warmup") is True

    class FakeRequest:
        async def json(self):
            return {"image_urls": ["http://example.com/a.jpg"]}

    result = asyncio.run(instance(FakeRequest()))
    assert result is sentinel_response
    assert seen["payload"] == {"image_urls": ["http://example.com/a.jpg"]}


def test_import_with_ray_requires_model_name(monkeypatch):
    """With Ray present, a missing MODEL_NAME fails at import, matching the
    reference's import-time raise (serve.py:199-201)."""
    monkeypatch.delenv("MODEL_NAME", raising=False)
    fakes = _make_fake_modules()
    with pytest.raises(ValueError, match="MODEL_NAME"):
        _reimport_app_with_fakes(fakes)
    # the failed reload left the fakes out of sys.modules; restore standalone
    importlib.reload(importlib.import_module(APP_MODULE))
    import spotter_tpu.serving.app as app

    assert app.deployment is None
