"""ISSUE 18 kernel parity suite: interpret-mode gates for the int8 attention
matmuls, the fused-prologue MSDA kernel, and the fused OWL-ViT logit head.

These are the CPU-side acceptance tests for the per-chip-throughput arc:
- `SPOTTER_TPU_INT8_ATTN` unset (or set without `SPOTTER_TPU_INT8`) keeps the
  forward bit-identical — the opt-out is asserted exactly, not approximately.
- `SPOTTER_TPU_MSDA_PREP=fused` keeps the param tree and (via its XLA
  fallback, which is also the VJP reference) the outputs bit-compatible with
  the unfused layer; the Pallas kernel is held to interpret-mode parity for
  both sampling methods, forward and backward.
- The fused OWL logit head matches the unfused tail, and NEG_INF masking
  guarantees padded/masked query slots can never win an argmax.
- Kernel dispatches self-report analytic FLOPs (XLA costs pallas
  custom-calls as 0) so MFU attribution stays honest on kernel paths.

Pallas runs in interpret mode on the CPU test mesh, same convention as
tests/test_msda.py.
"""

import os
import subprocess
import sys
from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import spotter_tpu.models.rtdetr as R
import spotter_tpu.ops.msda as M
import spotter_tpu.ops.openvocab as OV
import spotter_tpu.utils.quant as quant
from spotter_tpu.models.owlvit import OwlViTClassHead, OwlViTDetector
from spotter_tpu.models.rtdetr import RTDetrDetector
from spotter_tpu.models.zoo import tiny_owlvit_config, tiny_rtdetr_config
from spotter_tpu.obs.perf import collect_kernel_flops, combine_flops
from spotter_tpu.ops.msda import deformable_sampling_fused
from spotter_tpu.ops.openvocab import NEG_INF, fused_class_logits, pallas_class_logits

# ---------------------------------------------------------------------------
# fused-prologue MSDA: op-level parity (kernel interpret vs xla fallback)
# ---------------------------------------------------------------------------

SHAPES = ((8, 8), (4, 4))
B, Q, H, D, HD, P = 2, 70, 2, 32, 32, 2  # Q=70: exercises Q_TILE padding
LP = len(SHAPES) * P
S = sum(h * w for h, w in SHAPES)


def _fused_inputs(seed=0):
    rng = np.random.default_rng(seed)
    value = jnp.asarray(rng.standard_normal((B, S, H, HD)).astype(np.float32))
    hs = jnp.asarray(rng.standard_normal((B, Q, D)).astype(np.float32))
    # cxcywh in (0, 1) with non-degenerate wh
    ref = jnp.asarray(
        np.concatenate(
            [
                rng.uniform(0.2, 0.8, (B, Q, 2)),
                rng.uniform(0.2, 0.6, (B, Q, 2)),
            ],
            axis=-1,
        ).astype(np.float32)
    )
    w_off = jnp.asarray(
        (rng.standard_normal((D, H * LP * 2)) * 0.1).astype(np.float32)
    )
    b_off = jnp.asarray((rng.standard_normal((H * LP * 2,)) * 0.1).astype(np.float32))
    w_att = jnp.asarray((rng.standard_normal((D, H * LP)) * 0.1).astype(np.float32))
    b_att = jnp.asarray((rng.standard_normal((H * LP,)) * 0.1).astype(np.float32))
    return value, hs, ref, w_off, b_off, w_att, b_att


@pytest.mark.parametrize("method", ["default", "discrete"])
def test_fused_msda_kernel_matches_xla_fallback(method):
    args = _fused_inputs()
    got = deformable_sampling_fused(
        *args, SHAPES, P, method=method, backend="pallas", interpret=True
    )
    ref = deformable_sampling_fused(*args, SHAPES, P, method=method, backend="xla")
    assert got.shape == (B, Q, H * HD)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_fused_msda_grad_parity():
    """Extended custom VJP: gradients w.r.t. value, hidden states, and all
    four fused projection params must match the XLA reference path."""
    value, hs, ref, w_off, b_off, w_att, b_att = _fused_inputs(1)

    def loss(backend):
        def f(value, hs, w_off, b_off, w_att, b_att):
            out = deformable_sampling_fused(
                value, hs, ref, w_off, b_off, w_att, b_att, SHAPES, P,
                backend=backend, interpret=(backend == "pallas"),
            )
            return jnp.sum(jnp.sin(out))

        return jax.grad(f, argnums=(0, 1, 2, 3, 4, 5))(
            value, hs, w_off, b_off, w_att, b_att
        )

    g_k = loss("pallas")
    g_x = loss("xla")
    names = ("d_value", "d_hs", "d_w_off", "d_b_off", "d_w_att", "d_b_att")
    for name, a, b in zip(names, g_k, g_x):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, err_msg=name
        )


@pytest.fixture(scope="module")
def tiny_rtdetr():
    """Tiny RT-DETR + baseline forward, shared across the model-level tests
    (computed once under default knobs — every test patches knobs inside
    its body, after this resolves)."""
    cfg = tiny_rtdetr_config()
    model = RTDetrDetector(cfg)
    x = np.random.default_rng(0).standard_normal((2, 64, 64, 3)).astype(np.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    ref = model.apply({"params": params}, x)
    return model, params, x, ref


def test_fused_prep_model_param_tree_and_output_parity(monkeypatch, tiny_rtdetr):
    """SPOTTER_TPU_MSDA_PREP=fused on the tiny RT-DETR: the DenseParams
    declarations must produce the exact same param tree as the nn.Dense
    layers they replace (checkpoints interchange), and the XLA fallback —
    the fused op's reference numerics — must be bit-identical to the
    unfused layer. (Kernel-vs-fallback parity is pinned op-level above;
    kernel engagement through the model layer is pinned by the FLOPs test
    below, which lowers the forced-kernel model.)"""
    model, params, x, ref_out = tiny_rtdetr
    monkeypatch.setattr(M, "MSDA_PREP", "fused")
    fused_params = model.init(jax.random.PRNGKey(0), x)["params"]
    ref_paths = {
        "/".join(str(k) for k in p): v.shape
        for p, v in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    fused_paths = {
        "/".join(str(k) for k in p): v.shape
        for p, v in jax.tree_util.tree_flatten_with_path(fused_params)[0]
    }
    assert ref_paths == fused_paths, "param tree changed under MSDA_PREP=fused"

    # CPU host -> msda_backend picks xla -> fallback branch, the reference
    # numerics of the fused op: bit-identical to the unfused layer
    fb_out = model.apply({"params": params}, x)
    for key in ref_out:
        np.testing.assert_array_equal(
            np.asarray(ref_out[key]), np.asarray(fb_out[key]), err_msg=key
        )


def test_fused_prep_rejects_sg_and_nest():
    """SPOTTER_TPU_MSDA_SG / _NEST are xla-prep-only experiments; combining
    them with the fused prologue must fail loudly at import, not silently
    drop the subgroup/nest behavior."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "SPOTTER_TPU_MSDA": "pallas",  # SG needs the pallas backend first
        "SPOTTER_TPU_MSDA_PREP": "fused",
        "SPOTTER_TPU_MSDA_SG": "8",
    }
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.run(
        [sys.executable, "-c", "import spotter_tpu.ops.msda"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode != 0
    assert "SPOTTER_TPU_MSDA_SG requires SPOTTER_TPU_MSDA_PREP=xla" in proc.stderr


# ---------------------------------------------------------------------------
# int8 attention: guard truth table, exact opt-out, score/box tolerance
# ---------------------------------------------------------------------------


def test_int8_attn_guard_truth_table(monkeypatch):
    monkeypatch.setattr(quant, "INT8", True)
    monkeypatch.setattr(quant, "INT8_ATTN", True)
    monkeypatch.setattr(quant, "INT8_ATTN_MIN_HD", 32)
    monkeypatch.setattr(quant, "INT8_MIN_BATCH", 8)
    assert quant.int8_attn_wanted(64, batch=8)
    assert quant.int8_attn_wanted(32)  # batch unknown -> head_dim rules
    assert not quant.int8_attn_wanted(16, batch=8)  # below head-dim floor
    assert not quant.int8_attn_wanted(64, batch=4)  # below batch floor
    # "additionally" convention: INT8_ATTN rides on INT8, never alone
    monkeypatch.setattr(quant, "INT8", False)
    assert not quant.int8_attn_wanted(64, batch=8)
    monkeypatch.setattr(quant, "INT8", True)
    monkeypatch.setattr(quant, "INT8_ATTN", False)
    assert not quant.int8_attn_wanted(64, batch=8)


def test_int8_attn_opt_out_is_bit_identical(monkeypatch, tiny_rtdetr):
    """Acceptance gate: with SPOTTER_TPU_INT8_ATTN effectively off — here,
    set WITHOUT the base SPOTTER_TPU_INT8 opt-in — the forward must be
    bit-identical, not merely close. The quantized branch must be dead."""
    model, params, x, ref = tiny_rtdetr
    monkeypatch.setattr(quant, "INT8_ATTN", True)  # no INT8 -> still off
    monkeypatch.setattr(quant, "INT8_ATTN_MIN_HD", 1)
    monkeypatch.setattr(quant, "INT8_MIN_BATCH", 1)
    got = model.apply({"params": params}, x)
    for key in ref:
        np.testing.assert_array_equal(
            np.asarray(ref[key]), np.asarray(got[key]), err_msg=key
        )


def test_int8_attn_score_box_parity(monkeypatch, tiny_rtdetr):
    """int8 QK^T + attn.V live on the tiny RT-DETR (floors lowered to hit
    head_dim=8, batch=2; conv/dense quant floored out to isolate attention):
    scores and boxes stay within the same drift bar as the other int8
    surfaces, and the output provably changed (the path is live)."""
    model, params, x, ref = tiny_rtdetr
    monkeypatch.setattr(quant, "INT8", True)
    monkeypatch.setattr(quant, "INT8_ATTN", True)
    monkeypatch.setattr(quant, "INT8_ATTN_MIN_HD", 8)
    monkeypatch.setattr(quant, "INT8_MIN_BATCH", 1)
    monkeypatch.setattr(quant, "INT8_MIN_CH", 10**9)  # convs/denses stay float
    got = model.apply({"params": params}, x)
    assert not np.array_equal(
        np.asarray(ref["logits"]), np.asarray(got["logits"])
    ), "int8 attention path did not engage"
    score_ref = float(jax.nn.sigmoid(ref["logits"]).max())
    score_q = float(jax.nn.sigmoid(got["logits"]).max())
    assert abs(score_ref - score_q) < 0.05, (score_ref, score_q)
    box_ref = float(jnp.abs(ref["pred_boxes"]).mean())
    box_q = float(jnp.abs(got["pred_boxes"]).mean())
    assert abs(box_ref - box_q) < 0.05, (box_ref, box_q)


# ---------------------------------------------------------------------------
# fused OWL-ViT logit head: parity, NEG_INF masking, gradients
# ---------------------------------------------------------------------------

OWL_B, OWL_P, OWL_Q = 2, 65, 7  # P=65: exercises P_TILE padding


def _owl_head_inputs(seed=0):
    cfg = tiny_owlvit_config()
    rng = np.random.default_rng(seed)
    feats = jnp.asarray(
        rng.standard_normal((OWL_B, OWL_P, cfg.vision.hidden_size)).astype(np.float32)
    )
    queries = jnp.asarray(
        rng.standard_normal((OWL_Q, cfg.text.hidden_size)).astype(np.float32)
    )
    return cfg, feats, queries


@pytest.mark.parametrize("masked", [False, True])
def test_owl_fused_head_matches_unfused(monkeypatch, masked):
    cfg, feats, queries = _owl_head_inputs()
    qmask = (
        jnp.asarray(np.array([1, 1, 0, 1, 1, 0, 1], np.float32)) if masked else None
    )
    head = OwlViTClassHead(cfg)
    monkeypatch.setattr(OV, "OWL_FUSED", "0")
    params = head.init(jax.random.PRNGKey(0), feats, queries, qmask)["params"]
    ref = head.apply({"params": params}, feats, queries, qmask)
    monkeypatch.setattr(OV, "OWL_FUSED", "1")  # interpret auto-on off-TPU
    got = head.apply({"params": params}, feats, queries, qmask)
    assert got.shape == (OWL_B, OWL_P, OWL_Q)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got), atol=1e-6)
    if masked:
        assert np.all(np.asarray(got)[:, :, [2, 5]] == NEG_INF)


def test_owl_fused_mask_padded_slots_never_win_argmax(monkeypatch):
    """NEG_INF contract on the raw kernel output: lane-padded query slots
    (columns beyond the real query count) and caller-masked queries come out
    exactly NEG_INF, so an argmax over the padded width can only ever pick a
    real, unmasked query."""
    rng = np.random.default_rng(3)
    dt, q, qp, pp = 16, 7, OV.LANE, OV.P_TILE
    img = jnp.asarray(rng.standard_normal((1, pp, dt)).astype(np.float32))
    qt = jnp.zeros((dt, qp), jnp.float32)
    qbank = rng.standard_normal((dt, q)).astype(np.float32)
    qbank = qbank / np.linalg.norm(qbank, axis=0, keepdims=True)
    qt = qt.at[:, :q].set(jnp.asarray(qbank))
    ss = jnp.asarray(rng.standard_normal((1, pp, 2)).astype(np.float32))
    mask = jnp.zeros((1, qp), jnp.float32).at[0, :q].set(1.0)
    mask = mask.at[0, 4].set(0.0)  # caller-masked real query
    out = np.asarray(pallas_class_logits(img, qt, ss, mask, True))
    assert np.all(out[:, :, q:] == NEG_INF), "lane padding must be NEG_INF"
    assert np.all(out[:, :, 4] == NEG_INF), "masked query must be NEG_INF"
    winners = out.argmax(axis=-1).ravel()
    assert np.all(winners < q) and not np.any(winners == 4)


def test_owl_fused_head_grad_parity(monkeypatch):
    cfg, feats, queries = _owl_head_inputs(1)
    head = OwlViTClassHead(cfg)
    monkeypatch.setattr(OV, "OWL_FUSED", "0")
    params = head.init(jax.random.PRNGKey(0), feats, queries)["params"]

    def loss(feats_, params_):
        out = head.apply({"params": params_}, feats_, queries)
        return jnp.sum(jnp.tanh(out / 10.0))

    g_ref = jax.grad(loss, argnums=(0, 1))(feats, params)
    monkeypatch.setattr(OV, "OWL_FUSED", "1")
    g_fused = jax.grad(loss, argnums=(0, 1))(feats, params)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref), jax.tree_util.tree_leaves(g_fused)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_owl_fused_model_level_parity(monkeypatch):
    """Full tiny OWL-ViT detector under SPOTTER_TPU_OWL_FUSED=1: logits and
    boxes match the unfused forward (param tree is shared by construction —
    the fused branch reuses the same three Dense declarations)."""
    cfg = tiny_owlvit_config()
    model = OwlViTDetector(cfg)
    rng = np.random.default_rng(0)
    pixels = rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
    queries = jnp.asarray(
        rng.standard_normal((3, cfg.projection_dim)).astype(np.float32)
    )
    monkeypatch.setattr(OV, "OWL_FUSED", "0")
    params = model.init(jax.random.PRNGKey(0), pixels, queries)["params"]
    ref = model.apply({"params": params}, pixels, queries)
    monkeypatch.setattr(OV, "OWL_FUSED", "1")
    got = model.apply({"params": params}, pixels, queries)
    np.testing.assert_allclose(
        np.asarray(ref["logits"]), np.asarray(got["logits"]), atol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(ref["pred_boxes"]), np.asarray(got["pred_boxes"])
    )


# ---------------------------------------------------------------------------
# FLOPs honesty: kernel dispatches feed the MFU ledger
# ---------------------------------------------------------------------------


def test_fused_kernel_path_reports_flops(monkeypatch, tiny_rtdetr):
    """XLA's cost_analysis counts pallas custom-calls as 0 FLOPs; the fused
    MSDA dispatch must self-report its analytic count so combine_flops can
    repair the MFU denominator (finite, and strictly above what XLA alone
    credits the kernel-path program). Lowering the forced-kernel model also
    pins that MSDA_PREP=fused actually engages the kernel through the
    model layer."""
    model, params, x, _ = tiny_rtdetr
    monkeypatch.setattr(M, "MSDA_PREP", "fused")
    forced = partial(deformable_sampling_fused, backend="pallas", interpret=True)
    monkeypatch.setattr(M, "deformable_sampling_fused", forced)
    monkeypatch.setattr(R, "deformable_sampling_fused", forced)

    fwd = jax.jit(lambda p, xx: model.apply({"params": p}, xx))
    with collect_kernel_flops() as noted:
        lowered = fwd.lower(params, x)
    assert noted.get("msda_fused", 0) > 0, sorted(noted)
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca_flops = ca.get("flops") if hasattr(ca, "get") else None
    total = combine_flops(ca_flops, noted.get("__total__"))
    assert total is not None and np.isfinite(total) and total > 1e6
    if ca_flops is not None and np.isfinite(ca_flops) and ca_flops > 0:
        assert total > ca_flops  # the kernel's work was actually added
