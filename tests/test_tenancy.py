"""Multi-tenant isolation plane tests (ISSUE 19): token-bucket quota
properties, identity precedence, inflight caps, occupancy scoping,
deficit-weighted round-robin fairness (and its FIFO bit-identity opt-out),
the bounded /metrics tenant view, the noisy-neighbor chaos matrix, and the
table-driven 429/503 shed contract over the real HTTP surfaces."""

import asyncio
import json
import os
import random

import pytest
from aiohttp.test_utils import TestClient, TestServer

os.environ.setdefault("SPOTTER_TPU_TINY", "1")

from bench import _fmt as bench_fmt
from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.scheduler import QueueItem, Scheduler
from spotter_tpu.serving import tenancy
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.standalone import make_app
from spotter_tpu.serving.tenancy import (
    ANON,
    TENANT_CONFIG_ENV,
    TENANT_HEADER,
    TENANT_KEYS_ENV,
    TENANT_RPS_DEFAULT_ENV,
    TenantPlane,
    TenantQuotaError,
    TokenBucket,
)
from spotter_tpu.testing.chaos_matrix import (
    TENANT_MATRIX,
    run_tenant_scenario,
)
from spotter_tpu.testing.stub_engine import StubEngine, StubHttpClient


class FakeClock:
    def __init__(self, now: float = 1000.0) -> None:
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> None:
        self.now += dt


def _plane(config=None, **kw) -> TenantPlane:
    kw.setdefault("rng", random.Random(0))
    return TenantPlane(config=config, **kw)


# ------------------------------------------------- token bucket properties


def test_bucket_never_exceeds_burst():
    """Property: whatever the take/advance schedule, the token count never
    exceeds the burst capacity and never goes negative."""
    clock = FakeClock()
    bucket = TokenBucket(rate=10.0, burst=20.0, clock=clock)
    rng = random.Random(42)
    for _ in range(500):
        if rng.random() < 0.5:
            clock.advance(rng.uniform(0.0, 5.0))
        granted = bucket.try_take()
        assert 0.0 <= bucket.tokens <= bucket.burst
        if granted:
            assert bucket.tokens <= bucket.burst - 0.0
    # a long idle period refills to exactly burst, not beyond
    clock.advance(1e6)
    assert not bucket.try_take(bucket.burst + 1)
    assert bucket.try_take(bucket.burst)


def test_bucket_refill_is_monotone():
    """Property: with no takes, available tokens never decrease as time
    advances (in arbitrary increments)."""
    clock = FakeClock()
    bucket = TokenBucket(rate=3.0, burst=30.0, clock=clock)
    assert bucket.try_take(30.0)  # drain to zero
    rng = random.Random(7)
    last = 0.0
    for _ in range(200):
        clock.advance(rng.uniform(0.0, 1.0))
        bucket._refill(clock.now)
        assert bucket.tokens >= last - 1e-9
        last = bucket.tokens


def test_bucket_exact_quota_pacing_never_starves():
    """Arrival at exactly the sustained rate is admitted forever — the
    quota boundary belongs to the tenant, not the shedder."""
    clock = FakeClock()
    bucket = TokenBucket(rate=10.0, burst=20.0, clock=clock)
    assert bucket.try_take(20.0)  # start from an empty bucket: worst case
    for _ in range(1000):
        clock.advance(0.1)  # exactly 1 token per arrival at rate 10
        assert bucket.try_take()


def test_bucket_retry_after_tracks_deficit():
    clock = FakeClock()
    bucket = TokenBucket(rate=2.0, burst=2.0, clock=clock)
    assert bucket.try_take(2.0)
    assert not bucket.try_take()
    # 1 token at 2/s = 0.5 s away
    assert bucket.retry_after_s() == pytest.approx(0.5)
    clock.advance(0.25)
    assert bucket.retry_after_s() == pytest.approx(0.25)


# --------------------------------------------------- identity + admission


def test_identity_distrusts_bare_header():
    """REVIEW: the tenant header is client-controlled — bare, it must
    NOT be honored. Spoofers collapse to the key-resolved tenant or to
    the one shared anon bucket (id rotation gains nothing)."""
    plane = _plane(key_map={"sekrit": "acme"})
    # bare header: spoofable -> anon, and the reject is counted
    assert plane.resolve({TENANT_HEADER: "victim"}) == ANON
    assert plane.header_rejects_total == 1
    # valid key + mismatched header: the AUTHENTICATED identity wins
    assert plane.resolve(
        {TENANT_HEADER: "victim", "X-API-Key": "sekrit"}
    ) == "acme"
    # header matching the key-resolved tenant is honored
    assert plane.resolve(
        {TENANT_HEADER: "acme", "X-API-Key": "sekrit"}
    ) == "acme"
    assert plane.resolve({"X-API-Key": "sekrit"}) == "acme"
    assert plane.resolve({"X-API-Key": "unknown"}) == ANON
    assert plane.resolve({}) == ANON
    assert plane.resolve(None) == ANON
    snap = plane.snapshot()
    assert snap["header_rejects_total"] == 2  # victim x2 above
    assert snap["trust_header"] is False
    assert snap["edge_attested"] is False


def test_identity_edge_attestation_and_trust_opt_in():
    plane = _plane(edge_secret="shh")
    # the matching edge token attests the header (the edge->replica hop)
    headers: dict = {}
    plane.stamp(headers, "acme")
    assert headers[TENANT_HEADER] == "acme"
    assert headers[tenancy.EDGE_TOKEN_HEADER] == "shh"
    assert plane.resolve(headers) == "acme"
    # a wrong/missing token does not
    assert plane.resolve(
        {TENANT_HEADER: "acme", tenancy.EDGE_TOKEN_HEADER: "guess"}
    ) == ANON
    assert plane.resolve({TENANT_HEADER: "acme"}) == ANON
    # explicit deployment opt-in (attested upstream: mTLS/mesh) trusts bare
    trusting = _plane(trust_header=True)
    assert trusting.resolve({TENANT_HEADER: "acme"}) == "acme"
    assert trusting.header_rejects_total == 0
    # stamp without a secret forwards the id alone
    bare: dict = {}
    trusting.stamp(bare, "acme")
    assert bare == {TENANT_HEADER: "acme"}


def test_from_env_edge_secret_and_trust(monkeypatch, tmp_path):
    monkeypatch.setenv(TENANT_RPS_DEFAULT_ENV, "10")
    secret_file = tmp_path / "edge.secret"
    secret_file.write_text("filesecret\n")
    monkeypatch.setenv(tenancy.TENANT_EDGE_SECRET_ENV, str(secret_file))
    plane = tenancy.from_env()
    assert plane is not None and plane._edge_secret == "filesecret"
    assert plane.trust_header is False
    # a non-path value is the literal secret (test/drill ergonomics)
    monkeypatch.setenv(tenancy.TENANT_EDGE_SECRET_ENV, "inline-secret")
    monkeypatch.setenv(tenancy.TENANT_TRUST_HEADER_ENV, "1")
    plane = tenancy.from_env()
    assert plane._edge_secret == "inline-secret"
    assert plane.trust_header is True


def test_rate_quota_sheds_with_retry_after():
    clock = FakeClock()
    plane = _plane(
        config={"abuser": {"rps": 1.0, "burst": 2.0}}, clock=clock
    )
    plane.try_admit("abuser").release()
    plane.try_admit("abuser").release()
    with pytest.raises(TenantQuotaError) as exc_info:
        plane.try_admit("abuser")
    exc = exc_info.value
    assert exc.status == 429
    assert exc.kind == tenancy.SHED_RATE
    assert exc.tenant == "abuser"
    assert exc.retry_after_s >= 0.05
    snap = plane.snapshot()
    assert snap["tenants"]["abuser"]["sheds_rate_total"] == 1
    assert snap["sheds_total"]["rate"] == 1
    assert plane.admits_total == 2
    # refill un-sheds: the bucket, not a ban list
    clock.advance(1.0)
    plane.try_admit("abuser").release()


def test_inflight_cap_sheds_and_release_frees():
    plane = _plane(config={"loris": {"rps": 1000.0, "max_inflight": 2}})
    a = plane.try_admit("loris")
    b = plane.try_admit("loris")
    with pytest.raises(TenantQuotaError) as exc_info:
        plane.try_admit("loris")
    assert exc_info.value.kind == tenancy.SHED_INFLIGHT
    assert plane.snapshot()["tenants"]["loris"]["sheds_inflight_total"] == 1
    a.release()
    c = plane.try_admit("loris")  # a freed seat admits again
    # double-release is a no-op, not a double-free
    a.release()
    assert plane.inflight("loris") == 2
    b.release()
    c.release()
    assert plane.inflight("loris") == 0


def test_release_neutral_keeps_burn_untouched():
    """REVIEW leak guard: the abandoned-request release (good=None) frees
    the slot without recording an outcome — a disconnect flood must not
    poison (or credit) a tenant's SLO burn."""
    plane = _plane(config={"t": {"rps": 1000.0}})
    adm = plane.try_admit("t")
    adm.release(good=None)
    assert plane.inflight("t") == 0
    assert plane.snapshot()["tenants"]["t"]["slo_burn"] == 0.0
    # still exactly-once: a later release with an outcome is a no-op
    adm.release(good=False)
    assert plane.snapshot()["tenants"]["t"]["slo_burn"] == 0.0
    # contrast: a real bad outcome does burn
    plane.try_admit("t").release(good=False)
    assert plane.snapshot()["tenants"]["t"]["slo_burn"] > 0.0


def test_stale_inflight_tenants_are_evictable():
    """REVIEW backstop: leaked inflight slots (nothing live looks 10
    minutes old) must not make their tenants immortal, or a disconnecting
    tenant-id flood defeats the MAX_TRACKED_TENANTS memory bound."""
    clock = FakeClock()
    plane = _plane(clock=clock)
    held = [
        plane.try_admit(f"leak-{i:04d}")
        for i in range(tenancy.MAX_TRACKED_TENANTS)
    ]
    assert len(plane._tenants) == tenancy.MAX_TRACKED_TENANTS
    # every slot occupied and fresh: nothing evictable, the map holds
    plane.try_admit("fresh-a").release()
    assert len(plane._tenants) == tenancy.MAX_TRACKED_TENANTS
    assert "fresh-a" not in plane._tenants
    # past the stale horizon the leaked slots become reclaimable
    clock.advance(tenancy.INFLIGHT_STALE_S + 1.0)
    plane.try_admit("fresh-b").release()
    assert "fresh-b" in plane._tenants
    assert len(plane._tenants) <= tenancy.MAX_TRACKED_TENANTS
    del held


def test_over_share_and_top_occupancy():
    plane = _plane(config={"big": {"weight": 3.0}})
    grabbed = [plane.try_admit("hog") for _ in range(3)]
    one = plane.try_admit("small")
    # hog holds 3/4 of inflight on weight 1/2 of active weight
    assert plane.over_share("hog") is True
    assert plane.over_share("small") is False
    assert plane.over_share(None) is False
    assert plane.over_share("idle-unknown") is False
    assert plane.top_occupancy_tenant() == "hog"
    for adm in grabbed:
        adm.release()
    one.release()
    assert plane.top_occupancy_tenant() is None
    # weight normalizes occupancy: 2 inflight at weight 3 scores UNDER
    # 1 inflight at weight 1
    big = [plane.try_admit("big"), plane.try_admit("big")]
    small = plane.try_admit("tiny")
    assert plane.top_occupancy_tenant() == "tiny"
    for adm in big:
        adm.release()
    small.release()


def test_metrics_view_bounded_top_k_plus_other():
    plane = _plane(top_k=4)
    for i in range(12):
        for _ in range(12 - i):  # t00 admits most
            plane.try_admit(f"t{i:02d}").release()
    view = plane.metrics_view()
    assert len(view) == 5  # top 4 + "other"
    assert "other" in view
    assert set(view) > {"t00", "t01", "t02", "t03"}
    # nothing is lost to the bounding: totals add up
    total = sum(int(row["admits_total"]) for row in view.values())
    assert total == plane.admits_total
    # numeric-only rows: the prom renderer labels every stat
    for row in view.values():
        assert all(isinstance(v, float) for v in row.values())


def test_from_env_opt_out(monkeypatch):
    for env in (TENANT_KEYS_ENV, TENANT_CONFIG_ENV, TENANT_RPS_DEFAULT_ENV):
        monkeypatch.delenv(env, raising=False)
    assert tenancy.from_env() is None
    monkeypatch.setenv(TENANT_RPS_DEFAULT_ENV, "25")
    plane = tenancy.from_env()
    assert plane is not None and plane.default_rps == 25.0
    monkeypatch.delenv(TENANT_RPS_DEFAULT_ENV)
    monkeypatch.setenv(
        TENANT_CONFIG_ENV, '{"acme": {"rps": 100, "weight": 4}}'
    )
    plane = tenancy.from_env()
    assert plane is not None and plane.weight("acme") == 4.0


# --------------------------------------------------------------------- DRR


def _items(*tenants: str) -> list:
    return [f"{t}#{i}" for i, t in enumerate(tenants)]


def _tenant_of(item: str) -> str:
    return item.partition("#")[0]


def test_drr_single_tenant_is_identity():
    """Work-conserving degenerate case: one tenant (or zero) returns the
    INPUT LIST OBJECT — the bit-identity opt-out, assertable as `is`."""
    plane = _plane()
    items = _items("a", "a", "a")
    assert plane.drr_order(items, _tenant_of) is items
    empty: list = []
    assert plane.drr_order(empty, _tenant_of) is empty


def test_drr_equal_weights_round_robin():
    plane = _plane()
    items = _items("a", "a", "a", "b", "b", "b", "c", "c", "c")
    out = plane.drr_order(items, _tenant_of)
    assert sorted(out) == sorted(items)  # a permutation: nothing dropped
    assert [_tenant_of(x) for x in out] == [
        "a", "b", "c", "a", "b", "c", "a", "b", "c"
    ]
    # per-tenant arrival order is preserved inside the interleave
    assert [x for x in out if _tenant_of(x) == "a"] == [
        x for x in items if _tenant_of(x) == "a"
    ]


def test_drr_bounded_inter_tenant_gap():
    """Property: while every tenant still has queued items, any window of
    N consecutive grants serves all N tenants — no tenant waits more than
    one full round behind a backlog that isn't its own."""
    plane = _plane()
    tenants = ["a", "b", "c", "d"]
    items = _items(*(t for t in tenants for _ in range(8)))
    out = plane.drr_order(items, _tenant_of)
    n = len(tenants)
    # all tenants have equal depth, so every full window is a full round
    for i in range(0, len(out) - n + 1, n):
        assert {_tenant_of(x) for x in out[i:i + n]} == set(tenants)


def test_drr_weights_scale_service():
    plane = _plane(config={"heavy": {"weight": 2.0}})
    items = _items("heavy", "heavy", "heavy", "heavy", "light", "light")
    out = plane.drr_order(items, _tenant_of)
    # quantum = weight: heavy drains 2 per round for light's 1
    assert [_tenant_of(x) for x in out] == [
        "heavy", "heavy", "light", "heavy", "heavy", "light"
    ]


def test_drr_no_credit_survives_across_calls():
    """Classic DRR: a deficit resets when its queue empties, and every
    queue drains within a call — so NOTHING banks across calls (REVIEW:
    fairness is per-call by design, and a round-1 leftover must not
    reorder round 2)."""
    plane = _plane(config={"a": {"weight": 5.0}})
    # a's 5-credit quantum drains only 1 item here; leftover must not bank
    plane.drr_order(_items("a", "b", "b", "b"), _tenant_of)
    fresh = _plane(config={"a": {"weight": 5.0}})
    items = _items("b", "b", "b", "a", "a")
    assert plane.drr_order(list(items), _tenant_of) == fresh.drr_order(
        list(items), _tenant_of
    )


def test_scheduler_fifo_bit_identical_without_tenancy():
    sch = Scheduler(spec=None, ragged=False)  # tenancy=None: unconfigured
    items = [
        QueueItem(image=None, fut=None, tenant=t, t_submit=float(i))
        for i, t in enumerate(["a", "b", "a", "c", "b"])
    ]
    pending = list(items)
    plan = sch.plan(pending, target=5)
    assert plan.items == items  # exact arrival order
    assert all(x is y for x, y in zip(plan.items, items))  # same objects
    assert pending == []


def test_scheduler_fifo_bit_identical_single_tenant_with_plane():
    sch = Scheduler(spec=None, ragged=False, tenancy=_plane())
    items = [
        QueueItem(image=None, fut=None, tenant="only", t_submit=float(i))
        for i in range(4)
    ]
    pending = list(items)
    plan = sch.plan(pending, target=4)
    assert all(x is y for x, y in zip(plan.items, items))


def test_scheduler_fifo_drr_interleaves_tenants():
    sch = Scheduler(spec=None, ragged=False, tenancy=_plane())
    items = [
        QueueItem(image=None, fut=None, tenant=t, t_submit=float(i))
        for i, t in enumerate(["a", "a", "a", "b", "b", "b"])
    ]
    pending = list(items)
    plan = sch.plan(pending, target=6)
    assert [it.tenant for it in plan.items] == [
        "a", "b", "a", "b", "a", "b"
    ]


# -------------------------------------------------- noisy-neighbor matrix


@pytest.mark.slow
@pytest.mark.parametrize("sc", TENANT_MATRIX, ids=lambda sc: sc.name)
def test_tenant_matrix_row(sc):
    report = asyncio.run(run_tenant_scenario(sc))
    assert report["ok"], json.dumps(
        {k: v for k, v in report.items() if k != "plane"},
        indent=2,
        default=str,
    )


# ------------------------------------------------- HTTP surface contracts


def _stub_detector() -> AmenitiesDetector:
    eng = StubEngine(service_ms=1.0)
    return AmenitiesDetector(
        eng, MicroBatcher(eng, max_delay_ms=1.0), StubHttpClient()
    )


def test_unconfigured_server_has_no_tenancy_surface(monkeypatch):
    """The opt-out discipline, end to end: no tenancy env -> no plane
    object, no /metrics tenants block, /debug/tenants reports disabled."""
    for env in (TENANT_KEYS_ENV, TENANT_CONFIG_ENV, TENANT_RPS_DEFAULT_ENV):
        monkeypatch.delenv(env, raising=False)

    async def run():
        det = _stub_detector()
        app = make_app(detector=det)
        assert app["tenancy"] is None
        assert det.tenancy is None
        async with TestClient(TestServer(app)) as client:
            health = await (await client.get("/healthz")).json()
            assert health["tenancy"] == {"enabled": False}
            metrics = await (await client.get("/metrics")).json()
            assert "tenants" not in metrics
            dbg = await client.get("/debug/tenants")
            assert dbg.status == 200
            assert (await dbg.json()) == {"enabled": False}
            # requests with tenant headers still serve normally — the
            # header is inert without the plane
            r = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
                headers={TENANT_HEADER: "ghost"},
            )
            assert r.status == 200
        await det.aclose()

    asyncio.run(run())


def test_standalone_quota_shed_contract(monkeypatch):
    """The 429 contract at the replica edge: shed BEFORE parse, request-id
    echoed, Retry-After present, admit-shed counters charged, and the
    per-tenant rows visible in /metrics and /debug/tenants."""
    monkeypatch.setenv(
        TENANT_CONFIG_ENV, '{"default": {"rps": 1, "burst": 1}}'
    )
    # bare tenant headers are distrusted by default (REVIEW); this test
    # reads the shed CONTRACT, so opt the replica into header identity
    monkeypatch.setenv(tenancy.TENANT_TRUST_HEADER_ENV, "1")

    async def run():
        det = _stub_detector()
        app = make_app(detector=det)
        assert app["tenancy"] is not None
        async with TestClient(TestServer(app)) as client:
            headers = {TENANT_HEADER: "acme", "X-Request-ID": "rid-quota-1"}
            ok = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
                headers=headers,
            )
            assert ok.status == 200
            shed = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
                headers=headers,
            )
            assert shed.status == 429
            assert shed.headers["X-Request-ID"] == "rid-quota-1"
            assert "Retry-After" in shed.headers
            body = await shed.json()
            assert body["status"] == 429
            metrics = await (await client.get("/metrics")).json()
            assert metrics["shed_total"] >= 1
            assert sum(metrics["admit_sheds_total"].values()) >= 1
            assert metrics["tenants"]["acme"]["sheds_rate_total"] == 1
            assert metrics["tenants"]["acme"]["admits_total"] == 1
            prom = await (
                await client.get("/metrics?format=prometheus")
            ).text()
            assert (
                'spotter_tpu_tenants{tenant="acme",stat="sheds_rate_total"}'
                in prom
            )
            dbg = await (await client.get("/debug/tenants")).json()
            assert dbg["tenants"]["acme"]["sheds_rate_total"] == 1
        await det.aclose()

    asyncio.run(run())


def test_router_quota_shed_contract(monkeypatch):
    """The 429 contract at the fleet edge: quota charged BEFORE the body
    is read, request-id echoed, Retry-After + tenant named in the body,
    tenant identity forwarded to the replica, per-tenant /metrics rows."""
    for env in (TENANT_KEYS_ENV, TENANT_CONFIG_ENV, TENANT_RPS_DEFAULT_ENV):
        monkeypatch.delenv(env, raising=False)

    async def run():
        from spotter_tpu.obs.aggregate import FleetAggregator
        from spotter_tpu.serving.replica_pool import ReplicaPool
        from spotter_tpu.serving.router import make_router_app

        det = _stub_detector()
        replica_server = TestServer(make_app(detector=det))
        await replica_server.start_server()
        url = f"http://{replica_server.host}:{replica_server.port}"
        plane = _plane(
            config={"abuser": {"rps": 1.0, "burst": 1.0}},
            trust_header=True,  # clients model an attested upstream here
        )
        pool = ReplicaPool([url], health_interval_s=0.05)
        app = make_router_app(
            pool,
            aggregator=FleetAggregator(lambda: [], interval_s=0.0),
            tenancy_plane=plane,
        )
        async with TestClient(TestServer(app)) as client:
            headers = {TENANT_HEADER: "abuser", "X-Request-ID": "rid-r-1"}
            ok = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
                headers=headers,
            )
            assert ok.status == 200
            shed = await client.post(
                "/detect",
                json={"image_urls": ["http://example.com/a.jpg"]},
                headers=headers,
            )
            assert shed.status == 429
            assert shed.headers["X-Request-ID"] == "rid-r-1"
            assert "Retry-After" in shed.headers
            body = await shed.json()
            assert body["tenant"] == "abuser"
            metrics = await (await client.get("/metrics")).json()
            assert metrics["tenants"]["abuser"]["admits_total"] == 1
            assert metrics["tenants"]["abuser"]["sheds_rate_total"] == 1
            dbg = await (await client.get("/debug/tenants")).json()
            assert dbg["tenants"]["abuser"]["sheds_rate_total"] == 1
            health = await (await client.get("/healthz")).json()
            assert health["tenancy"] is True
        await pool.stop()
        await replica_server.close()
        await det.aclose()

    asyncio.run(run())


def test_router_releases_inflight_on_handler_crash():
    """REVIEW leak guard at the router edge: an exception the handler
    does NOT turn into a response (transport bug, cancellation) must
    still free the tenant's inflight slot — else a disconnecting client
    permanently 429-locks its tenant at max_inflight and skews
    top_occupancy/over_share forever."""

    async def run():
        from spotter_tpu.obs.aggregate import FleetAggregator
        from spotter_tpu.serving.replica_pool import ReplicaPool
        from spotter_tpu.serving.router import make_router_app

        plane = _plane(
            config={"t": {"rps": 1000.0, "max_inflight": 1}},
            trust_header=True,
        )
        pool = ReplicaPool(
            ["http://127.0.0.1:1"], health_interval_s=1000.0
        )

        async def boom(*a, **kw):
            raise RuntimeError("injected transport bug")

        pool.request = boom  # not PoolExhaustedError: escapes the handler
        app = make_router_app(
            pool,
            aggregator=FleetAggregator(lambda: [], interval_s=0.0),
            tenancy_plane=plane,
        )
        async with TestClient(TestServer(app)) as client:
            for i in range(3):  # > max_inflight: only a leak would 429
                r = await client.post(
                    "/detect",
                    json={"queries": ["sofa"]},
                    headers={TENANT_HEADER: "t"},
                )
                assert r.status == 500, f"request {i}: {r.status}"
            assert plane.inflight("t") == 0
            # no outcome was served: the crash must not burn the budget
            assert plane.snapshot()["tenants"]["t"]["slo_burn"] == 0.0
        await pool.stop()

    asyncio.run(run())


def test_standalone_releases_inflight_on_handler_crash(monkeypatch):
    """Same leak guard at the replica edge, via an admission-check path
    that raises outside every except clause."""
    monkeypatch.setenv(
        TENANT_CONFIG_ENV,
        '{"default": {"rps": 1000, "max_inflight": 1}}',
    )
    monkeypatch.setenv(tenancy.TENANT_TRUST_HEADER_ENV, "1")

    async def run():
        det = _stub_detector()

        def boom(*a, **kw):
            raise RuntimeError("injected check_admission bug")

        det.check_admission = boom
        app = make_app(detector=det)
        plane = app["tenancy"]
        async with TestClient(TestServer(app)) as client:
            for i in range(3):
                r = await client.post(
                    "/detect",
                    json={"image_urls": ["http://example.com/a.jpg"]},
                    headers={TENANT_HEADER: "t"},
                )
                assert r.status == 500, f"request {i}: {r.status}"
            assert plane.inflight("t") == 0
        await det.aclose()

    asyncio.run(run())


def test_retry_after_header_never_zero():
    """REVIEW: sub-second tenant hints (rate-shed jitter floors at
    0.05 s) must not render `Retry-After: 0` — that invites the
    immediate retry the shed exists to push back. The precise float
    rides in the JSON body instead."""
    from spotter_tpu.serving.router import tenant_shed_response
    from spotter_tpu.serving.standalone import _shed_response

    exc = TenantQuotaError("t", tenancy.SHED_RATE, retry_after_s=0.07)
    for resp in (tenant_shed_response(exc), _shed_response(exc)):
        assert int(resp.headers["Retry-After"]) >= 1
        assert json.loads(resp.body)["retry_after_s"] == 0.07
    # larger hints ceil, not truncate
    slow = TenantQuotaError("t", tenancy.SHED_RATE, retry_after_s=3.2)
    assert tenant_shed_response(slow).headers["Retry-After"] == "4"


def test_shed_contract_table_across_surfaces(monkeypatch):
    """Table-driven shed/reject contract: EVERY rejecting surface echoes
    the request id and returns a JSON error body with the status
    repeated — whichever layer rejected (tenant quota 429, the brownout
    bulk rung 503, batcher queue-full 429, model-routing 400). Load
    sheds carry Retry-After; routing 400s must NOT (a client defect —
    retrying it unchanged can never succeed) and name the registry
    instead so the caller can self-correct (ISSUE 20 parity)."""
    monkeypatch.delenv(TENANT_KEYS_ENV, raising=False)
    monkeypatch.delenv(TENANT_RPS_DEFAULT_ENV, raising=False)

    async def quota_app():
        det = _stub_detector()
        return det.aclose, make_app(detector=det), {TENANT_HEADER: "t"}, 429

    async def brownout_app():
        from spotter_tpu.serving.overload import BrownoutController

        eng = StubEngine(service_ms=1.0)
        clock = FakeClock()
        bc = BrownoutController(
            lambda: True, arm_s=1.0, disarm_s=100.0, clock=clock,
            metrics=eng.metrics,
        )
        det = AmenitiesDetector(
            eng,
            MicroBatcher(eng, max_delay_ms=1.0, brownout=bc),
            StubHttpClient(),
        )
        bc.evaluate()
        for _ in range(4):  # rung 4: bulk-only 503
            clock.advance(1.1)
            bc.evaluate()
        return (
            det.aclose, make_app(detector=det),
            {"X-Request-Class": "bulk"}, 503,
        )

    async def queue_full_app():
        eng = StubEngine(service_ms=200.0)
        det = AmenitiesDetector(
            eng,
            MicroBatcher(eng, max_delay_ms=200.0, max_queue=1),
            StubHttpClient(),
        )
        return det.aclose, make_app(detector=det), {}, 429

    async def routing_app():
        # closed-set single-family fleet edge with the autoscaler armed:
        # an unroutable request 400s BEFORE any pool access, so the pool
        # stays empty (target 0) and no member is ever needed
        from spotter_tpu.obs.aggregate import FleetAggregator
        from spotter_tpu.serving.autoscale import AutoscalerBrain, ModelPool
        from spotter_tpu.serving.fleet import (
            FleetController,
            PoolSpec,
            make_fleet_app,
        )

        controller = FleetController(
            [PoolSpec("rtdetr", spawner=lambda: None, target_size=0)],
            tick_s=0.05,
        )
        brain = AutoscalerBrain(
            controller,
            [ModelPool(model="rtdetr", min_size=0, max_size=1,
                       default=True)],
            tick_s=0.25,
        )
        app = make_fleet_app(
            controller,
            aggregator=FleetAggregator(lambda: [], interval_s=0.0),
            autoscaler=brain,
        )

        async def noop():
            return None

        return noop, app, {}, 400

    async def run():
        # (name, build, tenant_cfg, payload_extra, retry_after)
        rows = [
            ("tenant-quota", quota_app,
             '{"default": {"rps": 0.001, "burst": 1}}', {}, True),
            ("brownout-bulk", brownout_app, "", {}, True),
            ("queue-full", queue_full_app, "", {}, True),
            ("unknown-model", routing_app, "",
             {"model": "segment-anything"}, False),
            ("closed-set-queries", routing_app, "",
             {"queries": ["a solar panel"]}, False),
        ]
        for name, build, tenant_cfg, payload_extra, retry_after in rows:
            if tenant_cfg:
                monkeypatch.setenv(TENANT_CONFIG_ENV, tenant_cfg)
            else:
                monkeypatch.delenv(TENANT_CONFIG_ENV, raising=False)
            aclose, app, headers, want_status = await build()
            async with TestClient(TestServer(app)) as client:
                # concurrent burst: one request fills the quota/queue slot,
                # the rest hit the shed surface under test (routing rows
                # reject all 8 — the defect is in the request itself)
                resps = await asyncio.gather(*(
                    client.post(
                        "/detect",
                        json={
                            "image_urls": [f"http://example.com/{i}.jpg"],
                            **payload_extra,
                        },
                        headers={
                            **headers, "X-Request-ID": f"rid-{name}-{i}"
                        },
                    )
                    for i in range(8)
                ))
                sheds = [
                    (i, r) for i, r in enumerate(resps)
                    if r.status == want_status
                ]
                assert sheds, (
                    f"{name}: no {want_status} among "
                    f"{[r.status for r in resps]}"
                )
                for i, shed in sheds:
                    assert (
                        shed.headers["X-Request-ID"] == f"rid-{name}-{i}"
                    ), name
                    assert ("Retry-After" in shed.headers) is retry_after, (
                        f"{name}: Retry-After "
                        f"{'missing' if retry_after else 'present'}"
                    )
                    body = await shed.json()
                    assert body["status"] == want_status, name
                    if want_status == 400:
                        assert body["kind"] in (
                            "unknown_model", "closed_set_queries"
                        ), name
                        assert "rtdetr" in body["families"], name
                for _, r in enumerate(resps):
                    await r.read()
                metrics = await (await client.get("/metrics")).json()
                if want_status == 400:
                    block = metrics["autoscale"]
                    assert block["routing_rejections_total"] >= 8, name
                else:
                    assert metrics["shed_total"] >= 1, name
            await aclose()

    asyncio.run(run())


# ----------------------------------------- ADVICE round-5 leftover (bench)


def test_bench_fmt_none_guard():
    """bench.py `_fmt` (ADVICE round 5 #2): SLO-stat formatting must not
    TypeError when a stage stat is None (every batch errored)."""
    assert bench_fmt(None) == "n/a"
    assert bench_fmt(None, ".1f") == "n/a"
    assert bench_fmt(3.14159, ".1f") == "3.1"
    assert bench_fmt(42.0) == "42"
