"""Numerical parity: Flax DetrDetector vs HF torch DetrForObjectDetection.

Tiny random-init config, no network — the same guarantee pattern as
test_rtdetr_parity.py, including the padded-pixel-mask path (the reference's
DETR processor pads batches; serve.py:98 relies on the processor mask).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import DetrConfig as HFDetrConfig
from transformers import ResNetConfig as HFResNetConfig
from transformers.models.detr.modeling_detr import DetrForObjectDetection

from spotter_tpu.convert.detr_rules import detr_rules
from spotter_tpu.convert.torch_to_jax import convert_state_dict
from spotter_tpu.models.configs import DetrConfig
from spotter_tpu.models.detr import DetrDetector


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def _tiny_hf_config(layer_type="basic"):
    backbone = HFResNetConfig(
        embedding_size=8,
        hidden_sizes=[8, 12, 16, 24],
        depths=[1, 1, 1, 1],
        layer_type=layer_type,
        out_features=["stage4"],
    )
    return HFDetrConfig(
        use_timm_backbone=False,
        use_pretrained_backbone=False,
        backbone_config=backbone,
        d_model=32,
        encoder_layers=2,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        num_queries=9,
        num_labels=7,
    )


@pytest.mark.parametrize("layer_type", ["basic", "bottleneck"])
def test_detr_parity(layer_type):
    hf_cfg = _tiny_hf_config(layer_type)
    torch.manual_seed(0)
    model = DetrForObjectDetection(hf_cfg).eval()
    with torch.no_grad():
        for m in model.modules():
            if hasattr(m, "running_mean"):
                m.running_mean.uniform_(-0.2, 0.2)
                m.running_var.uniform_(0.8, 1.2)

    cfg = DetrConfig.from_hf(hf_cfg)
    params = convert_state_dict(model.state_dict(), detr_rules(cfg), strict=True)

    rng = np.random.default_rng(1)
    x = rng.uniform(0, 1, size=(2, 3, 64, 96)).astype(np.float32)
    # ragged valid regions exercise the mask-aware position embedding + padding
    mask = np.zeros((2, 64, 96), dtype=np.int64)
    mask[0, :64, :80] = 1
    mask[1, :48, :96] = 1

    with torch.no_grad():
        tout = model(torch.from_numpy(x), pixel_mask=torch.from_numpy(mask))

    jout = DetrDetector(cfg).apply(
        {"params": params},
        np.transpose(x, (0, 2, 3, 1)),
        mask.astype(np.float32),
    )

    np.testing.assert_allclose(
        np.asarray(jout["pred_boxes"]), tout.pred_boxes.numpy(), atol=2e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(jout["logits"]), tout.logits.numpy(), atol=5e-4, rtol=1e-3
    )
