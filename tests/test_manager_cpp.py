"""Build + run the C++ manager test binary under pytest.

Keeps `python -m pytest tests/` the single test entry point across the
Python serving stack and the native control plane (the reference splits
this across `go test` and `pytest` CI jobs — SURVEY.md §4.3).
"""

import shutil
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BUILD = REPO / "manager" / "build"


@pytest.mark.skipif(
    shutil.which("cmake") is None or shutil.which("g++") is None,
    reason="C++ toolchain not available",
)
def test_manager_cpp_suite():
    gen = ["-G", "Ninja"] if shutil.which("ninja") else []
    subprocess.run(
        ["cmake", "-S", str(REPO / "manager"), "-B", str(BUILD), *gen],
        check=True, capture_output=True,
    )
    subprocess.run(
        ["cmake", "--build", str(BUILD)], check=True, capture_output=True
    )
    result = subprocess.run(
        [str(BUILD / "manager_test")], capture_output=True, text=True, timeout=120
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "ALL MANAGER TESTS PASSED" in result.stdout
