"""Postprocess parity vs the torch/HF semantics the reference relies on
(serve.py:102-109): same top-k selection, label decoding, box scaling."""

import jax.numpy as jnp
import numpy as np

from spotter_tpu.ops.postprocess import (
    sigmoid_max_postprocess,
    sigmoid_topk_postprocess,
    softmax_postprocess,
    to_detections,
)


def _fake_outputs(b=2, q=10, c=5, seed=0):
    rng = np.random.default_rng(seed)
    logits = rng.normal(size=(b, q, c)).astype(np.float32)
    # valid normalized cxcywh boxes
    cxcy = rng.uniform(0.3, 0.7, size=(b, q, 2))
    wh = rng.uniform(0.05, 0.2, size=(b, q, 2))
    boxes = np.concatenate([cxcy, wh], axis=-1).astype(np.float32)
    sizes = np.array([[480.0, 640.0]] * b, dtype=np.float32)
    return logits, boxes, sizes


def test_sigmoid_topk_matches_numpy_reference(debug_nans):
    logits, boxes, sizes = _fake_outputs()
    k = 7
    scores, labels, out_boxes = sigmoid_topk_postprocess(
        jnp.asarray(logits), jnp.asarray(boxes), jnp.asarray(sizes), k=k
    )
    assert scores.shape == (2, k) and labels.shape == (2, k) and out_boxes.shape == (2, k, 4)

    # independent numpy reference implementing the HF RT-DETR selection
    for i in range(2):
        flat = 1.0 / (1.0 + np.exp(-logits[i].reshape(-1)))
        order = np.argsort(-flat)[:k]
        np.testing.assert_allclose(np.asarray(scores[i]), flat[order], rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(labels[i]), order % logits.shape[-1])
        qidx = order // logits.shape[-1]
        cx, cy, w, h = boxes[i, qidx].T
        expect = np.stack(
            [
                (cx - w / 2) * 640,
                (cy - h / 2) * 480,
                (cx + w / 2) * 640,
                (cy + h / 2) * 480,
            ],
            axis=-1,
        )
        np.testing.assert_allclose(np.asarray(out_boxes[i]), expect, rtol=1e-4)


def test_softmax_drops_no_object_class(debug_nans):
    logits, boxes, sizes = _fake_outputs(c=4)
    # make the "no object" (last) class dominant everywhere; it must be ignored
    logits[..., -1] = 100.0
    scores, labels, _ = softmax_postprocess(
        jnp.asarray(logits), jnp.asarray(boxes), jnp.asarray(sizes)
    )
    assert int(np.asarray(labels).max()) <= 2
    assert float(np.asarray(scores).max()) < 0.5


def test_sigmoid_max_labels_are_argmax():
    logits, boxes, sizes = _fake_outputs(c=3)
    scores, labels, _ = sigmoid_max_postprocess(
        jnp.asarray(logits), jnp.asarray(boxes), jnp.asarray(sizes)
    )
    np.testing.assert_array_equal(np.asarray(labels), logits.argmax(-1))


def test_to_detections_threshold_and_labels():
    scores = np.array([0.9, 0.4, 0.6])
    labels = np.array([0, 1, 2])
    boxes = np.array([[0, 0, 10, 10], [1, 1, 2, 2], [5, 5, 6, 6]], dtype=np.float32)
    id2label = {0: "tv", 1: "couch", 2: "chair"}
    dets = to_detections(scores, labels, boxes, id2label, threshold=0.5)
    assert [d["label"] for d in dets] == ["tv", "chair"]
    assert dets[0]["box"] == [0.0, 0.0, 10.0, 10.0]
