"""End-to-end numerical parity: Flax RTDetrDetector vs HF torch RT-DETR (v1 + v2).

Tiny random-init config (no network). This is the JAX-side guarantee behind the
reference's golden-box integration test (test_serve.py:293-300): if logits and
boxes match torch to ~1e-4 on random weights, converted real checkpoints
reproduce the golden boxes within the reference's own ±1 px tolerance.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import RTDetrConfig as HFRTDetrConfig
from transformers import RTDetrResNetConfig, RTDetrV2Config
from transformers.models.rt_detr.modeling_rt_detr import RTDetrForObjectDetection
from transformers.models.rt_detr_v2.modeling_rt_detr_v2 import RTDetrV2ForObjectDetection

from spotter_tpu.convert.rtdetr_rules import rtdetr_rules
from spotter_tpu.convert.torch_to_jax import convert_state_dict
from spotter_tpu.models.configs import RTDetrConfig
from spotter_tpu.models.rtdetr import RTDetrDetector


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def _tiny_configs(version=2, decoder_method="default"):
    backbone = RTDetrResNetConfig(
        embedding_size=16,
        hidden_sizes=[16, 24, 32, 48],
        depths=[1, 1, 1, 1],
        layer_type="basic",
        out_features=["stage2", "stage3", "stage4"],
    )
    config_cls = RTDetrV2Config if version == 2 else HFRTDetrConfig
    kwargs = {"decoder_method": decoder_method} if version == 2 else {}
    return config_cls(
        backbone_config=backbone,
        d_model=32,
        encoder_hidden_dim=32,
        encoder_in_channels=[24, 32, 48],
        decoder_in_channels=[32, 32, 32],
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        encoder_layers=1,
        decoder_layers=2,
        num_queries=12,
        num_labels=7,
        num_denoising=0,
        decoder_n_points=2,
        hidden_expansion=1.0,
        # default 0.01 init leaves many spatial positions with identical
        # encoder scores -> top-k tie order diverges between torch and jax;
        # larger init makes scores distinct so selection is deterministic
        initializer_range=0.2,
        **kwargs,
    )


def _parity(version, decoder_method="default"):
    hf_cfg = _tiny_configs(version, decoder_method)
    model_cls = RTDetrV2ForObjectDetection if version == 2 else RTDetrForObjectDetection
    torch.manual_seed(0)
    model = model_cls(hf_cfg).eval()
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, torch.nn.BatchNorm2d):
                m.running_mean.uniform_(-0.2, 0.2)
                m.running_var.uniform_(0.8, 1.2)

    cfg = RTDetrConfig.from_hf(hf_cfg)
    assert cfg.version == version
    assert cfg.decoder_method == decoder_method and cfg.decoder_offset_scale == 0.5
    params = convert_state_dict(model.state_dict(), rtdetr_rules(cfg), strict=False)

    rng = np.random.default_rng(1)
    x = rng.uniform(0, 1, size=(2, 3, 64, 64)).astype(np.float32)
    with torch.no_grad():
        tout = model(torch.from_numpy(x))

    jout = RTDetrDetector(cfg).apply(
        {"params": params}, np.transpose(x, (0, 2, 3, 1))
    )

    np.testing.assert_allclose(
        np.asarray(jout["pred_boxes"]), tout.pred_boxes.numpy(), atol=2e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(jout["logits"]), tout.logits.numpy(), atol=5e-4, rtol=1e-3
    )


def test_rtdetr_v2_parity_bilinear():
    _parity(2, "default")


def test_rtdetr_v2_parity_discrete():
    _parity(2, "discrete")


def test_rtdetr_v1_parity():
    """RT-DETR v1 (PekingU/rtdetr_r*vd, model_type rt_detr): same key layout,
    v1 deformable sampling == v2 'default' at offset_scale 0.5."""
    _parity(1)
