"""Multi-replica chaos integration (ISSUE 2 acceptance): two REAL supervised
server processes (stub engine, CPU) behind a ReplicaPool under concurrent
load; killing one replica mid-load (the preemption fault) must yield ZERO
client-visible request failures — every affected request is replayed to the
survivor — and the killed replica must return to ready via the supervisor,
with restarts_total visible in its /metrics.

Runs model-free under JAX_PLATFORMS=cpu; CI executes it in the existing
python test job (pull-request.yaml)."""

import asyncio
import signal
import time

import httpx
import pytest

from spotter_tpu.serving.replica_pool import ReplicaPool
from spotter_tpu.testing import cluster

PAYLOAD = {"image_urls": ["http://example.com/room.jpg"]}


@pytest.fixture
def two_replicas(tmp_path):
    replicas = cluster.start_replicas(2, str(tmp_path))
    try:
        yield replicas
    finally:
        for r in replicas:
            r.shutdown()


def test_kill_one_replica_zero_client_failures(two_replicas):
    victim, survivor = two_replicas

    async def run():
        pool = ReplicaPool(
            [victim.url, survivor.url],
            eject_threshold=1,
            backoff_base_s=0.2,
            health_interval_s=0.1,
        )
        await pool.start()
        results: list[dict] = []
        errors: list[BaseException] = []
        killed = {"pid": None, "at": None}

        async def one_request():
            try:
                results.append(await pool.detect(PAYLOAD))
            except BaseException as exc:  # any client-visible failure
                errors.append(exc)

        async def load(n=60, concurrency=8):
            sem = asyncio.Semaphore(concurrency)

            async def bounded():
                async with sem:
                    await one_request()

            await asyncio.gather(*(bounded() for _ in range(n)))

        async def chaos():
            # let some load flow both ways, then yank the victim's server
            await asyncio.sleep(0.3)
            killed["pid"] = victim.kill_child(signal.SIGKILL)
            killed["at"] = time.monotonic()

        await asyncio.gather(load(), chaos())
        await pool.stop()
        return results, errors, killed

    results, errors, killed = asyncio.run(run())

    # acceptance: zero client-visible failures through the pool
    assert errors == [], f"client saw {len(errors)} failures: {errors[:3]}"
    assert len(results) == 60
    assert all(r["amenities_description"] for r in results)
    assert killed["pid"] is not None

    # the killed replica returns to ready via the supervisor...
    back_in_s = cluster.wait_ready(victim.url, timeout_s=30.0)
    # ...and its metrics show the restart + a fresh time_to_ready gauge
    metrics = httpx.get(f"{victim.url}/metrics", timeout=5.0).json()
    assert metrics["restarts_total"] == 1
    assert metrics["time_to_ready_s"] > 0
    # post-recovery traffic reaches it directly (not just via the pool)
    direct = httpx.post(f"{victim.url}/detect", json=PAYLOAD, timeout=10.0)
    assert direct.status_code == 200
    assert back_in_s < 30.0


def test_preemption_file_drains_then_supervisor_restarts(tmp_path):
    """The maintenance-event path end-to-end across processes: touching the
    watched file makes the replica drain (readiness flips first) and exit
    with the distinct preemption code; the supervisor restarts it without
    crash-loop backoff debt."""
    marker = tmp_path / "maintenance-event"
    replicas = cluster.start_replicas(
        1,
        str(tmp_path),
        env={
            "SPOTTER_TPU_PREEMPTION_FILE": str(marker),
            "SPOTTER_TPU_PREEMPTION_POLL_S": "0.05",
        },
    )
    (replica,) = replicas
    try:
        pid_before = replica.child_pid()
        marker.write_text("scheduled maintenance")
        # the replica must die (preemption exit) and come back as a NEW
        # process via the supervisor...
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            pid_now = replica.child_pid()
            if pid_now is not None and pid_now != pid_before:
                break
            time.sleep(0.05)
        assert replica.child_pid() != pid_before, "supervisor never respawned"
        # ... but the marker still exists: remove it so the NEW child does
        # not immediately preempt itself again, then wait for ready
        marker.unlink()
        cluster.wait_ready(replica.url, timeout_s=30.0)
        metrics = httpx.get(f"{replica.url}/metrics", timeout=5.0).json()
        assert metrics["restarts_total"] >= 1
    finally:
        out = replica.shutdown()
    assert "preempted" in out  # supervisor logged the distinct exit path


def test_affinity_routing_rehashes_on_replica_death(two_replicas):
    """ISSUE 11 acceptance: kill 1 of 2 replicas mid-load under
    cache-affinity routing — every key whose owner died must rehash to the
    deterministic next-highest-weight holder (the ring's failover order
    rides into ReplicaPool.request(prefer=...)) with ZERO client-visible
    failures, and the router must keep answering with correctly-ordered
    multi-URL responses throughout."""
    from aiohttp.test_utils import TestClient, TestServer

    from spotter_tpu.serving.router import make_router_app

    victim, survivor = two_replicas
    # distinct URLs spread over both owners: a mixed-key request fans out
    urls = [f"http://example.com/listing-{i}.jpg" for i in range(6)]

    async def run():
        pool = ReplicaPool(
            [victim.url, survivor.url],
            eject_threshold=1,
            backoff_base_s=0.2,
            health_interval_s=0.1,
        )
        app = make_router_app(pool, affinity=True)
        errors: list = []
        bodies: list[dict] = []
        killed = {"pid": None}
        async with TestClient(TestServer(app)) as client:

            async def one_request():
                try:
                    resp = await client.post(
                        "/detect", json={"image_urls": urls}
                    )
                    assert resp.status == 200, await resp.text()
                    bodies.append(await resp.json())
                except BaseException as exc:
                    errors.append(exc)

            async def load(n=40, concurrency=6):
                sem = asyncio.Semaphore(concurrency)

                async def bounded():
                    async with sem:
                        await one_request()

                await asyncio.gather(*(bounded() for _ in range(n)))

            async def chaos():
                await asyncio.sleep(0.3)
                killed["pid"] = victim.kill_child(signal.SIGKILL)

            await asyncio.gather(load(), chaos())
            metrics = await (await client.get("/metrics")).json()
        return errors, bodies, killed, metrics

    errors, bodies, killed, metrics = asyncio.run(run())
    assert killed["pid"] is not None
    assert errors == [], f"affinity routing leaked {len(errors)}: {errors[:3]}"
    assert len(bodies) == 40
    # fan-in order held through the failover: every response carries every
    # URL, in request order
    for body in bodies:
        assert [img["url"] for img in body["images"]] == urls
        assert body["amenities_description"]
    # the data plane actually routed with the ring, and the dead owner's
    # keys fell to the survivor (fallback served at least one sub-request)
    assert metrics["affinity"]["enabled"] is True
    assert metrics["affinity"]["routed_total"] > 0
    assert metrics["affinity"]["fallback_total"] > 0, (
        "no key ever fell to a lower-ranked holder — the kill was invisible?"
    )
    # the killed replica comes back via its supervisor
    cluster.wait_ready(victim.url, timeout_s=30.0)


def test_drain_window_stays_clean_through_pool(two_replicas):
    """Graceful path: draining one replica (preStop) mid-load must also be
    invisible — the pool sees 503s and routes around it."""
    draining, survivor = two_replicas

    async def run():
        pool = ReplicaPool(
            [draining.url, survivor.url],
            eject_threshold=1,
            backoff_base_s=0.2,
            health_interval_s=0.1,
        )
        await pool.start()
        errors = []

        async def load(n=30):
            sem = asyncio.Semaphore(6)

            async def one():
                async with sem:
                    try:
                        await pool.detect(PAYLOAD)
                    except BaseException as exc:
                        errors.append(exc)

            await asyncio.gather(*(one() for _ in range(n)))

        async def drain_mid_load():
            await asyncio.sleep(0.2)
            async with httpx.AsyncClient() as client:
                resp = await client.post(f"{draining.url}/drain", timeout=10.0)
                assert resp.status_code == 200

        await asyncio.gather(load(), drain_mid_load())
        await pool.stop()
        return errors

    errors = asyncio.run(run())
    assert errors == [], f"drain window leaked failures: {errors[:3]}"
    # drained replica reports unready; the pool health loop keeps it out
    health = httpx.get(f"{draining.url}/healthz", timeout=5.0)
    assert health.status_code == 503
