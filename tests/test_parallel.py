"""Mesh + sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4.4)."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from spotter_tpu.engine.engine import InferenceEngine
from spotter_tpu.models.rtdetr import RTDetrDetector
from spotter_tpu.models.zoo import tiny_rtdetr_config
from spotter_tpu.parallel import (
    RTDETR_TP_RULES,
    data_sharding,
    make_mesh,
    param_shardings,
    shard_params,
    spec_for_path,
)
from spotter_tpu.engine.engine import BuiltDetector
from spotter_tpu.ops.preprocess import PreprocessSpec


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape == {"dp": 8, "tp": 1}
    mesh = make_mesh(tp=2)
    assert mesh.shape == {"dp": 4, "tp": 2}
    mesh = make_mesh(dp=2, tp=2)
    assert mesh.shape == {"dp": 2, "tp": 2}
    with pytest.raises(ValueError):
        make_mesh(tp=3)  # 8 % 3 != 0
    with pytest.raises(ValueError):
        make_mesh(dp=8, tp=2)  # needs 16 devices


def test_tp_rule_matching():
    assert spec_for_path("decoder_layer0/fc1/kernel", RTDETR_TP_RULES) == P(None, "tp")
    assert spec_for_path("decoder_layer0/fc2/kernel", RTDETR_TP_RULES) == P("tp", None)
    assert spec_for_path("aifi0_layer0/self_attn/q_proj/kernel", RTDETR_TP_RULES) == P(
        None, "tp"
    )
    assert spec_for_path("aifi0_layer0/self_attn/out_proj/kernel", RTDETR_TP_RULES) == P(
        "tp", None
    )
    # backbone convs and norms stay replicated
    assert spec_for_path("backbone/stem0/conv/kernel", RTDETR_TP_RULES) == P()
    assert spec_for_path("decoder_layer0/fc1/nothing", RTDETR_TP_RULES) == P()


@pytest.fixture(scope="module")
def tiny_model():
    cfg = tiny_rtdetr_config()
    module = RTDetrDetector(cfg)
    params = module.init(jax.random.PRNGKey(0), np.zeros((1, 64, 64, 3), np.float32))[
        "params"
    ]
    return cfg, module, params


def test_param_shardings_tree(tiny_model):
    _, _, params = tiny_model
    mesh = make_mesh(dp=4, tp=2)
    shardings = param_shardings(params, mesh, RTDETR_TP_RULES)
    flat = jax.tree_util.tree_leaves_with_path(shardings)
    assert all(isinstance(s, NamedSharding) for _, s in flat)
    # at least one TP-sharded leaf and most leaves replicated
    specs = [s.spec for _, s in flat]
    assert P(None, "tp") in specs
    assert specs.count(P()) > len(specs) // 2


@pytest.mark.slow  # compile-heavy on 1-core CPU; full/CI run covers it
def test_sharded_forward_matches_single_device(tiny_model):
    """DP+TP sharded forward == single-device forward (same params, inputs)."""
    cfg, module, params = tiny_model
    x = np.random.default_rng(0).standard_normal((4, 64, 64, 3)).astype(np.float32)

    ref = module.apply({"params": params}, x)

    mesh = make_mesh(dp=4, tp=2)
    sharded_params = shard_params(params, mesh, RTDETR_TP_RULES)
    xs = jax.device_put(x, data_sharding(mesh))
    out = jax.jit(lambda p, v: module.apply({"params": p}, v))(sharded_params, xs)

    np.testing.assert_allclose(
        np.asarray(out["logits"]), np.asarray(ref["logits"]), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(out["pred_boxes"]), np.asarray(ref["pred_boxes"]), atol=1e-5
    )


@pytest.mark.slow  # real R18 train step on the CPU mesh; full/CI run covers it
def test_dryrun_real_r18_architecture_sharded():
    """The REAL rtdetr_v2_r18vd architecture (real d_model/heads/layer names)
    trains one dp×tp-sharded step on the virtual 8-device mesh — so the TP
    rule set is validated against the real param tree, not just the tiny
    config (VERDICT r2 weak #4)."""
    import __graft_entry__ as graft

    # conftest.py already forced the 8-device CPU mesh in this process, so
    # the impl runs inline (no subprocess re-exec).
    assert jax.device_count() >= 8
    graft._dryrun_multichip_impl(8, preset="rtdetr_v2_r18vd")


def test_dp2_serving_engine_fast_tier(tiny_model):
    """Fast-tier dp=2 smoke (ISSUE 3): the REAL serving path — engine with a
    dp=2 mesh fed by the MicroBatcher at the aggregate bucket — over the
    virtual CPU devices. The batcher fills dp × per-chip bucket in one
    dispatch and detections match the single-chip path at the same config.
    dp-only (tp=1) keeps per-image compute identical, so boxes match tightly.
    """
    import asyncio

    from PIL import Image

    from spotter_tpu.engine.batcher import MicroBatcher

    cfg, module, params = tiny_model
    spec = PreprocessSpec(mode="fixed", size=(64, 64))
    built = BuiltDetector(
        model_name="tiny",
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="sigmoid_topk",
        id2label=cfg.id2label_dict,
        num_top_queries=10,
    )
    rng = np.random.default_rng(2)
    images = [
        Image.fromarray(rng.integers(0, 255, (80, 100, 3), np.uint8))
        for _ in range(4)
    ]
    per_chip = 2
    single = InferenceEngine(built, threshold=0.0, batch_buckets=(per_chip,))
    mesh = make_mesh(dp=2, tp=1)
    # aggregate bucket = dp × per-chip (what serving/app.py configures)
    sharded = InferenceEngine(
        built, threshold=0.0, batch_buckets=(2 * per_chip,), mesh=mesh
    )
    batcher = MicroBatcher(sharded, max_delay_ms=50.0)
    assert batcher.max_batch == 4  # fills the aggregate bucket

    async def drive():
        results = await asyncio.gather(*(batcher.submit(im) for im in images))
        await batcher.stop()
        return results

    via_batcher = asyncio.run(drive())
    snap = sharded.metrics.snapshot()
    assert snap["aggregate_bucket"] == 4
    # all four concurrent submits ride ONE aggregate dispatch
    assert snap["batches_total"] == 1 and snap["mean_batch_size"] == 4.0
    assert snap["h2d_bytes_total"] > 0

    reference = single.detect(images)
    assert len(via_batcher) == len(reference) == 4
    for da, db in zip(reference, via_batcher):
        assert [d["label"] for d in da] == [d["label"] for d in db]
        np.testing.assert_allclose(
            np.asarray([d["box"] for d in da], np.float32),
            np.asarray([d["box"] for d in db], np.float32),
            atol=1e-4,
        )


def test_dp2_device_preprocess_sharded_matches(tiny_model):
    """uint8 ingest + dp sharding compose: same detections as the host-float
    single-chip path (the two tentpole halves run together in prod)."""
    from PIL import Image

    cfg, module, params = tiny_model
    spec = PreprocessSpec(mode="fixed", size=(64, 64))
    built = BuiltDetector(
        model_name="tiny",
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="sigmoid_topk",
        id2label=cfg.id2label_dict,
        num_top_queries=10,
    )
    rng = np.random.default_rng(3)
    images = [
        Image.fromarray(rng.integers(0, 255, (60, 90, 3), np.uint8))
        for _ in range(4)
    ]
    single = InferenceEngine(built, threshold=0.0, batch_buckets=(4,))
    sharded = InferenceEngine(
        built, threshold=0.0, batch_buckets=(4,), mesh=make_mesh(dp=2, tp=1),
        device_preprocess=True,
    )
    a = single.detect(images)
    b = sharded.detect(images)
    for da, db in zip(a, b):
        assert [d["label"] for d in da] == [d["label"] for d in db]
        np.testing.assert_allclose(
            np.asarray([d["box"] for d in da], np.float32),
            np.asarray([d["box"] for d in db], np.float32),
            atol=1e-3,
        )


@pytest.mark.slow  # compile-heavy on 1-core CPU; full/CI run covers it
def test_engine_with_mesh_matches_unsharded(tiny_model):
    """The serving engine produces identical detections with and without a mesh."""
    from PIL import Image

    cfg, module, params = tiny_model
    spec = PreprocessSpec(mode="fixed", size=(64, 64))
    built = BuiltDetector(
        model_name="tiny",
        module=module,
        params=params,
        preprocess_spec=spec,
        postprocess="sigmoid_topk",
        id2label=cfg.id2label_dict,
        num_top_queries=10,
    )
    rng = np.random.default_rng(1)
    images = [
        Image.fromarray(rng.integers(0, 255, (80, 100, 3), np.uint8)) for _ in range(5)
    ]

    plain = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2, 4, 8))
    mesh = make_mesh(dp=4, tp=2)
    sharded = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2, 4, 8), mesh=mesh)
    # buckets got rounded up to multiples of dp=4
    assert all(b % 4 == 0 for b in sharded.batch_buckets)

    a = plain.detect(images)
    b = sharded.detect(images)
    assert len(a) == len(b) == 5
    for da, db in zip(a, b):
        assert [d["label"] for d in da] == [d["label"] for d in db]
        np.testing.assert_allclose(
            np.asarray([d["box"] for d in da], np.float32),
            np.asarray([d["box"] for d in db], np.float32),
            atol=1e-2,
        )
