"""Replica-lifecycle tests (ISSUE 2): startup state machine + /startupz,
preemption watcher (file source + explicit trigger), admin-token guard on
state-changing endpoints, compile-cache env plumbing, and the /metrics
lifecycle fields surviving a drain/restart cycle."""

import asyncio
import os
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.serving import lifecycle
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.resilience import CircuitBreaker
from spotter_tpu.serving.standalone import ADMIN_TOKEN_ENV, ADMIN_TOKEN_HEADER, make_app
from spotter_tpu.testing.stub_engine import StubEngine, StubHttpClient


def _detector():
    engine = StubEngine()
    batcher = MicroBatcher(
        engine,
        max_delay_ms=1.0,
        breaker=CircuitBreaker(threshold=100, metrics=engine.metrics),
    )
    return AmenitiesDetector(engine, batcher, StubHttpClient()), engine


# ---- startup state machine ----


def test_startup_tracker_transitions():
    tracker = lifecycle.StartupTracker()
    assert tracker.state == lifecycle.LOADING and not tracker.ready
    tracker.mark(lifecycle.WARMING)
    assert tracker.state == lifecycle.WARMING and not tracker.ready
    engine = StubEngine()
    ttr = tracker.mark_ready(engine.metrics)
    assert tracker.ready and ttr > 0
    assert engine.metrics.snapshot()["time_to_ready_s"] == ttr
    with pytest.raises(ValueError):
        tracker.mark("bogus")


def test_startupz_endpoint_with_prebuilt_detector():
    detector, engine = _detector()

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.get("/startupz")
            assert resp.status == 200
            body = await resp.json()
            assert body["state"] == "ready"
            assert body["time_to_ready_s"] > 0

    asyncio.run(run())


def test_startupz_503_while_loading_and_detect_shed():
    """While bring-up runs, /startupz and /healthz answer 503 (startupProbe
    territory), /livez 200, and /detect sheds with Retry-After instead of
    erroring — then everything flips once the build completes."""

    async def run(monkeypatch_release: asyncio.Event):
        app = make_app(detector=None, model_name="unused")

        # substitute a slow bring-up for the real model build
        async def fake_bring_up(app):
            await monkeypatch_release.wait()
            det, engine = _detector()
            app["startup"].mark(lifecycle.WARMING)
            app["detector"] = det
            app["startup"].mark_ready(engine.metrics)

        async def start_fake_bring_up(app):
            app["bringup_task"] = asyncio.create_task(fake_bring_up(app))

        app.on_startup.clear()
        app.on_startup.append(start_fake_bring_up)
        async with TestClient(TestServer(app)) as client:
            startup = await client.get("/startupz")
            assert startup.status == 503
            assert (await startup.json())["state"] == "loading"
            health = await client.get("/healthz")
            assert health.status == 503
            live = await client.get("/livez")
            assert live.status == 200
            shed = await client.post("/detect", json={"image_urls": ["http://x/y.jpg"]})
            assert shed.status == 503
            assert "Retry-After" in shed.headers
            metrics = await client.get("/metrics")
            assert (await metrics.json())["startup"]["state"] == "loading"

            monkeypatch_release.set()
            for _ in range(100):
                startup = await client.get("/startupz")
                if startup.status == 200:
                    break
                await asyncio.sleep(0.01)
            assert startup.status == 200
            ok = await client.post("/detect", json={"image_urls": ["http://x/y.jpg"]})
            assert ok.status == 200
            await app["detector"].batcher.stop()

    asyncio.run(run(asyncio.Event()))


def test_startup_tracker_mark_failed():
    tracker = lifecycle.StartupTracker()
    tracker.mark_failed("RuntimeError: boom")
    assert tracker.state == lifecycle.FAILED and not tracker.ready
    snap = tracker.snapshot()
    assert snap["state"] == "failed" and snap["error"] == "RuntimeError: boom"


def test_bringup_failure_marks_failed_and_exits(monkeypatch):
    """A bring-up that raises must not wedge the replica in 'loading'
    forever: it marks the terminal failed state (visible on /startupz for
    whatever probe window remains) and exits non-zero so the supervisor's
    crash-loop/backoff machinery — which only reacts to process exit — can
    take over."""
    import spotter_tpu.serving.standalone as standalone

    def exploding_build(model_name):
        raise RuntimeError("boom: no such model")

    monkeypatch.setattr(standalone, "_build_detector_blocking", exploding_build)
    exit_codes = []

    async def run():
        app = make_app(model_name="nonexistent", bringup_exit_cb=exit_codes.append)
        async with TestClient(TestServer(app)) as client:
            for _ in range(200):
                if exit_codes:
                    break
                await asyncio.sleep(0.01)
            assert exit_codes == [lifecycle.BRINGUP_FAILED_EXIT_CODE]
            startup = await client.get("/startupz")
            assert startup.status == 503
            body = await startup.json()
            assert body["state"] == "failed"
            assert "boom" in body["error"]
            live = await client.get("/livez")
            assert live.status == 200  # exit_cb stubbed: process still serves

    asyncio.run(run())


# ---- preemption watcher ----


def test_preemption_file_source_drains_and_exits(tmp_path):
    """The maintenance-file source: file appears -> readiness flips via
    drain() -> distinct exit code handed to exit_cb. No SIGTERM involved."""
    detector, engine = _detector()
    marker = tmp_path / "preempt-now"
    exit_codes = []

    async def run():
        watcher = lifecycle.PreemptionWatcher(
            on_preempt=detector.drain,
            poll_s=0.02,
            file_source=str(marker),
            url_source=None,
            exit_cb=exit_codes.append,
            install_sigterm=False,
        )
        await watcher.start()
        await asyncio.sleep(0.1)
        assert not watcher.preempted  # no event yet
        marker.write_text("maintenance")
        for _ in range(200):
            if exit_codes:
                break
            await asyncio.sleep(0.01)
        assert exit_codes == [lifecycle.PREEMPTED_EXIT_CODE]
        assert watcher.preempted and "maintenance file" in watcher.reason
        assert detector.batcher.draining  # drain actually ran
        await watcher.stop()

    asyncio.run(run())
    assert engine.metrics.snapshot()["draining"] is True


def test_preemption_trigger_is_idempotent():
    drains = []

    async def run():
        async def on_preempt():
            drains.append(1)

        exit_codes = []
        watcher = lifecycle.PreemptionWatcher(
            on_preempt=on_preempt,
            poll_s=0.02,
            file_source=None,
            url_source=None,
            exit_cb=exit_codes.append,
            install_sigterm=False,
        )
        await watcher.start()
        watcher.trigger("SIGTERM")
        watcher.trigger("SIGTERM again")  # must not double-drain
        for _ in range(100):
            if exit_codes:
                break
            await asyncio.sleep(0.01)
        assert drains == [1]
        assert exit_codes == [lifecycle.PREEMPTED_EXIT_CODE]
        assert watcher.reason == "SIGTERM"
        await watcher.stop()

    asyncio.run(run())


# ---- warm restart plumbing ----


def test_compile_cache_env(monkeypatch, tmp_path):
    cache_dir = tmp_path / "compile-cache"
    monkeypatch.setenv(lifecycle.COMPILE_CACHE_ENV, str(cache_dir))
    assert lifecycle.maybe_enable_compile_cache() == str(cache_dir)
    assert cache_dir.is_dir()
    import jax

    assert jax.config.jax_compilation_cache_dir == str(cache_dir)

    monkeypatch.delenv(lifecycle.COMPILE_CACHE_ENV)
    assert lifecycle.maybe_enable_compile_cache() is None


def test_restarts_from_env(monkeypatch):
    monkeypatch.delenv(lifecycle.RESTARTS_ENV, raising=False)
    assert lifecycle.restarts_from_env() == 0
    monkeypatch.setenv(lifecycle.RESTARTS_ENV, "3")
    assert lifecycle.restarts_from_env() == 3
    monkeypatch.setenv(lifecycle.RESTARTS_ENV, "garbage")
    assert lifecycle.restarts_from_env() == 0


# ---- admin-token guard ----


def test_admin_endpoints_open_when_token_unset(monkeypatch):
    monkeypatch.delenv(ADMIN_TOKEN_ENV, raising=False)
    detector, _ = _detector()

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            drained = await client.post("/drain")
            assert drained.status == 200

    asyncio.run(run())


def test_admin_endpoints_guarded_when_token_set(monkeypatch):
    monkeypatch.setenv(ADMIN_TOKEN_ENV, "s3cret")
    detector, _ = _detector()

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            # missing and wrong tokens are rejected before any state changes
            no_token = await client.post("/drain")
            assert no_token.status == 401
            wrong = await client.post("/drain", headers={ADMIN_TOKEN_HEADER: "nope"})
            assert wrong.status == 401
            profile_no_token = await client.post("/profile", json={})
            assert profile_no_token.status == 401
            # the replica kept serving: the failed drains changed nothing
            health = await client.get("/healthz")
            assert health.status == 200
            # correct token drains
            ok = await client.post("/drain", headers={ADMIN_TOKEN_HEADER: "s3cret"})
            assert ok.status == 200
            assert (await ok.json())["status"] == "drained"

    asyncio.run(run())


# ---- /metrics lifecycle fields across drain/restart ----


def test_metrics_lifecycle_fields_survive_drain_restart(monkeypatch):
    """time_to_ready_s and restarts_total are process-lifetime gauges: a
    batcher drain + restart (the in-process analog of readiness flapping)
    must not reset them."""
    monkeypatch.setenv(lifecycle.RESTARTS_ENV, "2")
    detector, engine = _detector()

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            snap = await (await client.get("/metrics")).json()
            assert snap["time_to_ready_s"] > 0
            assert snap["restarts_total"] == 2

            await client.post("/drain")
            snap_drained = await (await client.get("/metrics")).json()
            assert snap_drained["draining"] is True
            assert snap_drained["time_to_ready_s"] == snap["time_to_ready_s"]
            assert snap_drained["restarts_total"] == 2

            # explicit re-open (the supervisor-restart analog inside one
            # process) keeps the gauges
            await detector.batcher.start()
            ok = await client.post("/detect", json={"image_urls": ["http://x/a.jpg"]})
            assert ok.status == 200
            snap_restarted = await (await client.get("/metrics")).json()
            assert snap_restarted["draining"] is False
            assert snap_restarted["time_to_ready_s"] == snap["time_to_ready_s"]
            assert snap_restarted["restarts_total"] == 2

    asyncio.run(run())


# ---- multihost coordinator timeout (satellite) ----


def test_coordinator_timeout_default_and_env(monkeypatch):
    from spotter_tpu.parallel import multihost

    monkeypatch.delenv(multihost.COORD_TIMEOUT_ENV, raising=False)
    assert multihost.coordinator_timeout_s() == multihost.DEFAULT_COORD_TIMEOUT_S
    monkeypatch.setenv(multihost.COORD_TIMEOUT_ENV, "45")
    assert multihost.coordinator_timeout_s() == 45
    assert multihost.multihost_env_summary()["SPOTTER_TPU_COORD_TIMEOUT_S"] == "45"
    for bad in ("abc", "0", "-5"):
        monkeypatch.setenv(multihost.COORD_TIMEOUT_ENV, bad)
        with pytest.raises(ValueError):
            multihost.coordinator_timeout_s()


def test_initialize_passes_timeout_to_jax(monkeypatch):
    """The env knob must actually reach jax.distributed.initialize as
    initialization_timeout — the whole point is failing fast on a dead
    coordinator."""
    import jax

    from spotter_tpu.parallel import multihost

    captured = {}

    def fake_initialize(**kwargs):
        captured.update(kwargs)

    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    monkeypatch.setenv(multihost.COORD_TIMEOUT_ENV, "17")
    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    monkeypatch.setattr(multihost, "_distributed_is_initialized", lambda: False)
    assert multihost.initialize_multihost() is True
    assert captured["initialization_timeout"] == 17
    assert captured["num_processes"] == 2
    assert captured["process_id"] == 0


def test_initialize_wraps_coordinator_failure(monkeypatch):
    import jax

    from spotter_tpu.parallel import multihost

    def exploding_initialize(**kwargs):
        raise RuntimeError("DEADLINE_EXCEEDED: connect to coordinator")

    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    monkeypatch.setattr(jax.distributed, "initialize", exploding_initialize)
    monkeypatch.setattr(multihost, "_distributed_is_initialized", lambda: False)
    with pytest.raises(RuntimeError, match="multihost bring-up failed"):
        multihost.initialize_multihost()


def test_time_to_ready_anchor_is_monotonic():
    # _PROCESS_START is captured at module import; mark_ready measured from
    # it must be >= any tracker's own age
    tracker = lifecycle.StartupTracker()
    time.sleep(0.01)
    ttr = tracker.mark_ready()
    assert ttr >= 0.01
    assert tracker.snapshot()["time_to_ready_s"] == ttr


def test_stub_engine_detects_and_records_metrics():
    engine = StubEngine(service_ms=1.0)
    out = engine.detect([object(), object()])
    assert len(out) == 2 and out[0][0]["label"] == "tv"
    snap = engine.metrics.snapshot()
    assert snap["images_total"] == 2


def test_pool_label_from_env(monkeypatch):
    """SPOTTER_TPU_POOL is a pure label (set by the fleet spawner) surfaced
    through /startupz + /healthz so capacity classes are tellable apart."""
    from spotter_tpu.serving import lifecycle

    monkeypatch.delenv("SPOTTER_TPU_POOL", raising=False)
    assert lifecycle.pool_from_env() is None
    tracker = lifecycle.StartupTracker()
    assert tracker.snapshot()["pool"] is None
    monkeypatch.setenv("SPOTTER_TPU_POOL", "spot")
    assert lifecycle.pool_from_env() == "spot"
    assert tracker.snapshot()["pool"] == "spot"


# ---- supervisor policy (in-process; the cross-process path is in
# tests/test_failover.py) ----


def test_supervisor_backoff_jitter_desynchronizes():
    """ISSUE 6 satellite: two supervisors preempted by the same maintenance
    wave must NOT re-enter backoff in lockstep. With full jitter (default
    on) their waits decorrelate while the deterministic doubling cap — the
    thing the crash-loop window is calibrated against — stays identical."""
    import random
    import sys

    from spotter_tpu.serving.supervisor import Supervisor

    cmd = [sys.executable, "-c", "pass"]
    a = Supervisor(cmd, rng=random.Random(1), jitter=True)
    b = Supervisor(cmd, rng=random.Random(2), jitter=True)
    seq_a = [a._bump_backoff() for _ in range(6)]
    seq_b = [b._bump_backoff() for _ in range(6)]
    assert seq_a != seq_b  # desynchronized waits
    caps = [0.5, 1.0, 2.0, 4.0, 8.0, 16.0]
    for wait_a, wait_b, cap in zip(seq_a, seq_b, caps):
        assert 0.0 <= wait_a <= cap
        assert 0.0 <= wait_b <= cap
    assert a._backoff_s == b._backoff_s == 16.0  # identical cap trajectory
    # jitter off: the exact exponential sequence, reproducible
    c = Supervisor(cmd, jitter=False)
    assert [c._bump_backoff() for _ in range(3)] == [0.5, 1.0, 2.0]
    # env knob: explicit 0 disables, unset enables
    import os

    from spotter_tpu.serving.supervisor import jitter_enabled_from_env

    old = os.environ.pop("SPOTTER_TPU_BACKOFF_JITTER", None)
    try:
        assert jitter_enabled_from_env()
        os.environ["SPOTTER_TPU_BACKOFF_JITTER"] = "0"
        assert not jitter_enabled_from_env()
    finally:
        os.environ.pop("SPOTTER_TPU_BACKOFF_JITTER", None)
        if old is not None:
            os.environ["SPOTTER_TPU_BACKOFF_JITTER"] = old


def test_supervisor_crash_loop_circuit():
    import sys

    from spotter_tpu.serving.supervisor import CRASH_LOOP_EXIT_CODE, Supervisor

    sup = Supervisor(
        [sys.executable, "-c", "import sys; sys.exit(1)"],
        backoff_base_s=0.02,
        backoff_max_s=0.05,
        min_uptime_s=1.0,
        crash_loop_limit=3,
    )
    assert sup.run() == CRASH_LOOP_EXIT_CODE
    assert sup.restarts_total == 3  # circuit tripped before the 4th respawn


def test_supervisor_clean_exit_propagates():
    import sys

    from spotter_tpu.serving.supervisor import Supervisor

    sup = Supervisor([sys.executable, "-c", "pass"])
    assert sup.run() == 0
    assert sup.restarts_total == 0


def test_supervisor_exports_restart_count_and_pidfile(tmp_path):
    """Each spawn exports SPOTTER_TPU_RESTARTS and rewrites the pidfile —
    the plumbing behind restarts_total in /metrics and behind harnesses
    targeting the current child."""
    import sys

    from spotter_tpu.serving.supervisor import Supervisor

    out = tmp_path / "restarts.log"
    pidfile = tmp_path / "child.pid"
    script = (
        "import os, sys\n"
        f"with open({str(out)!r}, 'a') as f:\n"
        "    f.write(os.environ['SPOTTER_TPU_RESTARTS'] + '\\n')\n"
        "sys.exit(0 if os.environ['SPOTTER_TPU_RESTARTS'] == '2' else 1)\n"
    )
    sup = Supervisor(
        [sys.executable, "-c", script],
        backoff_base_s=0.02,
        backoff_max_s=0.05,
        min_uptime_s=1.0,
        crash_loop_limit=10,
        pidfile=str(pidfile),
    )
    assert sup.run() == 0  # third generation (RESTARTS=2) exits cleanly
    assert out.read_text().split() == ["0", "1", "2"]
    assert pidfile.exists() and int(pidfile.read_text()) > 0


def test_supervisor_sigterm_during_backoff_exits_without_respawn():
    """REVIEW fix: SIGTERM landing while no child runs (mid-backoff) must
    end the supervisor with the last child's code — not resume the sleep
    (PEP 475) and spawn a fresh child the signal can never reach."""
    import sys
    import threading

    from spotter_tpu.serving.supervisor import Supervisor

    sup = Supervisor(
        [sys.executable, "-c", "import sys; sys.exit(1)"],
        backoff_base_s=10.0,  # far longer than the test: must be interrupted
        min_uptime_s=1.0,
        crash_loop_limit=10,
    )
    # the handler body is what SIGTERM would run; invoking it from a timer
    # thread exercises the same code path without needing a real signal
    threading.Timer(0.5, sup._forward_term, args=(None, None)).start()
    started = time.monotonic()
    assert sup.run() == 1  # the crashed child's code, not a fresh spawn's
    assert time.monotonic() - started < 5.0  # backoff wait was interrupted
    assert sup.restarts_total == 0  # no respawn after termination


def test_supervisor_persistent_preemption_falls_back_to_backoff(tmp_path):
    """REVIEW fix: when the preemption source outlives the child (marker
    file never deleted), exit-83 restarts must not hot-loop — after
    `preempt_fast_limit` consecutive fast preemption exits the normal
    exponential backoff applies. Preemption exits never trip the
    crash-loop circuit."""
    import sys

    from spotter_tpu.serving.supervisor import Supervisor

    counter = tmp_path / "count"
    script = (
        "import pathlib, sys\n"
        f"p = pathlib.Path({str(counter)!r})\n"
        "n = int(p.read_text()) + 1 if p.exists() else 1\n"
        "p.write_text(str(n))\n"
        "sys.exit(83 if n <= 5 else 0)\n"
    )
    sup = Supervisor(
        [sys.executable, "-c", script],
        backoff_base_s=0.2,
        backoff_max_s=0.4,
        min_uptime_s=5.0,  # every child exit here counts as "fast"
        crash_loop_limit=3,  # < the 5 preemption exits: must NOT trip
        preempt_fast_limit=2,
        jitter=False,  # this test times the deterministic cap trajectory
    )
    started = time.monotonic()
    assert sup.run() == 0
    elapsed = time.monotonic() - started
    assert sup.restarts_total == 5
    # exits 3..5 were past the fast limit: backoffs 0.2 + 0.4 + 0.4 = 1.0 s
    assert elapsed >= 0.9


@pytest.mark.skipif(os.name != "posix", reason="posix-only")
def test_preemption_env_source_construction(monkeypatch, tmp_path):
    """Env-driven construction: file/url/poll knobs are read when the
    constructor args are left at None."""
    monkeypatch.setenv(lifecycle.PREEMPTION_FILE_ENV, str(tmp_path / "m"))
    monkeypatch.setenv(lifecycle.PREEMPTION_POLL_ENV, "0.5")
    monkeypatch.delenv(lifecycle.PREEMPTION_URL_ENV, raising=False)

    async def noop():
        pass

    watcher = lifecycle.PreemptionWatcher(on_preempt=noop, install_sigterm=False)
    assert watcher.file_source == str(tmp_path / "m")
    assert watcher.url_source is None
    assert watcher.poll_s == 0.5


def test_supervisor_fatal_engine_exit_restarts_immediately(tmp_path):
    """ISSUE 4: FATAL_ENGINE_EXIT_CODE (85) gets an immediate warm restart
    — no crash backoff, no crash-loop debt — but a device that STAYS dead
    falls back to backoff after the fast limit, like persistent preemption."""
    import sys

    from spotter_tpu.engine.errors import FATAL_ENGINE_EXIT_CODE
    from spotter_tpu.serving.supervisor import Supervisor

    counter = tmp_path / "count"
    script = (
        "import pathlib, sys\n"
        f"p = pathlib.Path({str(counter)!r})\n"
        "n = int(p.read_text()) + 1 if p.exists() else 1\n"
        "p.write_text(str(n))\n"
        f"sys.exit({FATAL_ENGINE_EXIT_CODE} if n <= 2 else 0)\n"
    )
    sup = Supervisor(
        [sys.executable, "-c", script],
        backoff_base_s=5.0,  # immediate restarts must never hit this
        min_uptime_s=5.0,  # every exit here counts as "fast"
        crash_loop_limit=1,  # fatal-engine exits must NOT trip the circuit
        preempt_fast_limit=3,
    )
    started = time.monotonic()
    assert sup.run() == 0
    assert sup.restarts_total == 2
    assert time.monotonic() - started < 4.0  # no 5 s backoff was paid


def test_supervisor_persistent_fatal_engine_falls_back_to_backoff(tmp_path):
    """A chip that stays dead (exit 85 forever-fast) must not hot-loop
    spawn->fatal->exit: past the fast limit the exponential backoff applies."""
    import sys

    from spotter_tpu.engine.errors import FATAL_ENGINE_EXIT_CODE
    from spotter_tpu.serving.supervisor import Supervisor

    counter = tmp_path / "count"
    script = (
        "import pathlib, sys\n"
        f"p = pathlib.Path({str(counter)!r})\n"
        "n = int(p.read_text()) + 1 if p.exists() else 1\n"
        "p.write_text(str(n))\n"
        f"sys.exit({FATAL_ENGINE_EXIT_CODE} if n <= 4 else 0)\n"
    )
    sup = Supervisor(
        [sys.executable, "-c", script],
        backoff_base_s=0.2,
        backoff_max_s=0.3,
        min_uptime_s=5.0,
        crash_loop_limit=2,  # < the 4 fatal exits: must NOT trip
        preempt_fast_limit=2,
        jitter=False,  # this test times the deterministic cap trajectory
    )
    started = time.monotonic()
    assert sup.run() == 0
    elapsed = time.monotonic() - started
    assert sup.restarts_total == 4
    # exits 3 and 4 were past the fast limit: backoffs 0.2 + 0.3 = 0.5 s
    assert elapsed >= 0.45
