"""Caching tier (ISSUE 5): content-addressed result cache + single-flight
coalescing, exercised through the REAL detector/batcher plumbing with a fake
engine (the quantity under test is the cache/coalescing machinery, not the
forward pass).

Covers the acceptance + edge matrix: N concurrent identical-URL requests do
exactly 1 fetch and <= 1 engine call; a waiter's deadline expiring mid-flight
fails only that waiter; a shared-flight poison fans `PoisonImageError` to
every waiter exactly once AND fills the negative cache (so a repeat skips the
bisect machinery); eviction respects the byte budget under concurrent fill;
negative-cache TTL expiry really re-attempts the fetch; retryable failures
(5xx) are never cached; `SPOTTER_TPU_CACHE_MAX_MB=0` constructs none of the
tier (bit-identical admission behavior); injected cache faults degrade to
misses, never failed requests.
"""

import asyncio
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from io import BytesIO

import httpx
import numpy as np
import pytest
from PIL import Image

from spotter_tpu.caching.result_cache import ResultCache, content_key, url_key
from spotter_tpu.caching.singleflight import SingleFlight
from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.errors import PoisonImageError
from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.resilience import (
    CircuitBreaker,
    Deadline,
    DeadlineExceededError,
)
from spotter_tpu.testing import faults

DETS = [{"label": "tv", "score": 0.9, "box": [1.0, 2.0, 20.0, 30.0]}]


@pytest.fixture(autouse=True)
def _zero_retry_backoff(monkeypatch):
    import spotter_tpu.serving.detector as det_mod

    monkeypatch.setattr(det_mod, "FETCH_RETRY_WAIT_MIN_S", 0.0)
    monkeypatch.setattr(det_mod, "FETCH_RETRY_WAIT_MAX_S", 0.0)


class FakeEngine:
    def __init__(self, service_s: float = 0.0, detections=DETS):
        self.metrics = Metrics()
        self.batch_buckets = (1, 2, 4, 8)
        self.threshold = 0.5
        self.calls: list[int] = []
        self.service_s = service_s
        self.detections = detections

    def detect(self, images):
        self.calls.append(len(images))
        if self.service_s:
            time.sleep(self.service_s)
        return [list(self.detections) for _ in images]


class FailingEngine(FakeEngine):
    def detect(self, images):
        self.calls.append(len(images))
        raise RuntimeError("synthetic model failure")


class BrightPoisonEngine(FakeEngine):
    """Fails any batch containing a bright (mean > 200) image — the
    deterministic per-input failure shape the bisect-retry isolates to a
    `PoisonImageError` once a co-batched innocent proves the engine works."""

    def detect(self, images):
        self.calls.append(len(images))
        if any(np.asarray(im).mean() > 200 for im in images):
            raise RuntimeError("bright image poisoned its batch")
        return [list(self.detections) for _ in images]


def _jpeg(seed: int = 0) -> bytes:
    img = Image.fromarray(np.full((16, 16, 3), seed % 256, np.uint8))
    buf = BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


class CountingClient:
    """Duck-typed httpx client: per-URL fetch counts, per-URL content, an
    optional latency, and optional canned failures."""

    def __init__(self, latency_s: float = 0.0, fail_with=None, content_for=None):
        self.fetches: dict[str, int] = {}
        self.latency_s = latency_s
        self.fail_with = fail_with  # callable(url) -> response
        self.content_for = content_for  # callable(url) -> bytes

    async def get(self, url: str):
        self.fetches[url] = self.fetches.get(url, 0) + 1
        if self.latency_s:
            await asyncio.sleep(self.latency_s)
        if self.fail_with is not None:
            return self.fail_with(url)
        body = (
            self.content_for(url)
            if self.content_for is not None
            else _jpeg(abs(hash(url)) % 251)
        )

        class _Resp:
            content = body

            def raise_for_status(self):
                pass

        return _Resp()

    async def aclose(self):
        pass


def _img():
    return Image.fromarray(np.zeros((8, 8, 3), np.uint8))


def _detector(engine, client=None, cache=None, **batcher_kwargs):
    batcher_kwargs.setdefault("max_delay_ms", 1.0)
    batcher = MicroBatcher(engine, **batcher_kwargs)
    return AmenitiesDetector(
        engine, batcher, client or CountingClient(), cache=cache
    )


def _cache(engine, max_bytes=1 << 20, **kwargs):
    return ResultCache(max_bytes=max_bytes, metrics=engine.metrics, **kwargs)


# --- acceptance: N identical concurrent requests -> 1 fetch, <= 1 engine call


def test_concurrent_identical_urls_one_fetch_one_engine_call():
    engine = FakeEngine(service_s=0.01)
    client = CountingClient(latency_s=0.01)
    det = _detector(engine, client, cache=_cache(engine))

    async def run():
        payload = {"image_urls": ["http://cdn/x.jpg"] * 8}
        resp = await det.detect(payload)
        assert all(isinstance(d.detections, list) for d in resp.images)
        await det.aclose()

    asyncio.run(run())
    assert client.fetches == {"http://cdn/x.jpg": 1}
    assert sum(engine.calls) <= 1
    snap = engine.metrics.snapshot()
    assert snap["coalesced_fetches_total"] == 7
    assert snap["coalesced_submits_total"] == 7


def test_repeat_request_is_cache_hit_no_engine_call():
    engine = FakeEngine()
    det = _detector(engine, cache=_cache(engine))

    async def run():
        await det.detect({"image_urls": ["http://cdn/a.jpg"]})
        calls_after_first = sum(engine.calls)
        resp = await det.detect({"image_urls": ["http://cdn/a.jpg"]})
        assert isinstance(resp.images[0].detections, list)
        assert resp.images[0].detections[0].label == "TV"
        assert sum(engine.calls) == calls_after_first  # served from cache
        await det.aclose()

    asyncio.run(run())
    snap = engine.metrics.snapshot()
    assert snap["cache_hits_total"] == 1
    assert snap["cache_entries"] == 1


# --- coalescing edges ---------------------------------------------------------


def test_waiter_deadline_expires_mid_flight_others_succeed():
    engine = FakeEngine(service_s=0.25)
    batcher = MicroBatcher(engine, max_delay_ms=1.0)

    async def run():
        img = _img()
        t_ok = asyncio.create_task(batcher.submit(img, key="k"))
        await asyncio.sleep(0.05)  # flight is queued/dispatched
        with pytest.raises(DeadlineExceededError):
            await batcher.submit(
                _img(), deadline=Deadline.after(0.05), key="k"
            )
        assert await t_ok == DETS  # the shared flight survived the expiry
        await batcher.stop()

    asyncio.run(run())
    assert sum(engine.calls) == 1
    assert engine.metrics.snapshot()["deadline_exceeded_total"] == 1


def test_shared_flight_poison_fans_to_all_waiters_exactly_once():
    engine = BrightPoisonEngine()
    cache = ResultCache(max_bytes=1 << 20, metrics=engine.metrics)
    batcher = MicroBatcher(
        engine,
        max_delay_ms=50.0,  # wide window: poison + innocent share one batch
        breaker=CircuitBreaker(threshold=100, metrics=engine.metrics),
        result_cache=cache,
    )
    poison = Image.fromarray(np.full((8, 8, 3), 255, np.uint8))
    observed: list[BaseException] = []

    async def run():
        async def one():
            try:
                await batcher.submit(poison, key="poisoned")
            except PoisonImageError as exc:
                observed.append(exc)

        innocent = asyncio.create_task(batcher.submit(_img()))
        await asyncio.gather(*(one() for _ in range(5)))
        assert await innocent == DETS  # co-batched innocent succeeded
        await batcher.stop()

    asyncio.run(run())
    # every waiter saw the poison exactly once, off ONE coalesced queue entry
    assert len(observed) == 5
    assert len({id(e) for e in observed}) == 1  # the same fanned instance
    # 1 original batch + its bisect halves — never one call per waiter
    assert len(engine.calls) == 3 and engine.calls[0] == 2
    # ... and the verdict landed in the negative cache for repeat traffic
    assert isinstance(cache.get_negative("poisoned"), PoisonImageError)
    assert engine.metrics.snapshot()["poison_isolated_total"] == 1


def test_repeat_poison_skips_bisect_via_negative_cache():
    engine = BrightPoisonEngine()

    def content(url):
        return _jpeg(255) if "poison" in url else _jpeg(0)

    det = _detector(
        engine,
        CountingClient(content_for=content),
        cache=_cache(engine),
        breaker=CircuitBreaker(threshold=100, metrics=engine.metrics),
        max_delay_ms=50.0,
    )

    async def run():
        r1 = await det.detect(
            {"image_urls": ["http://cdn/poison.jpg", "http://cdn/ok.jpg"]}
        )
        by_url = {i.url: i for i in r1.images}
        assert "PoisonImageError" in by_url["http://cdn/poison.jpg"].error
        assert isinstance(by_url["http://cdn/ok.jpg"].detections, list)
        engine_calls = len(engine.calls)
        r2 = await det.detect({"image_urls": ["http://cdn/poison.jpg"]})
        assert "Processing Error" in r2.images[0].error
        assert len(engine.calls) == engine_calls  # no re-bisect, no engine work
        await det.aclose()

    asyncio.run(run())
    assert engine.metrics.snapshot()["cache_negative_hits_total"] == 1


def test_draining_shared_flight_not_cached():
    """A keyed flight failed by shutdown (the 429/503 shed family) must fan
    the error to its waiters but never write a cache entry."""
    engine = FakeEngine(service_s=10.0)  # never completes inside the test
    cache = ResultCache(max_bytes=1 << 20, metrics=engine.metrics)
    batcher = MicroBatcher(engine, max_delay_ms=50.0, result_cache=cache)

    async def run():
        tasks = [
            asyncio.create_task(batcher.submit(_img(), key="k"))
            for _ in range(3)
        ]
        await asyncio.sleep(0.02)
        # fail the queued entry without running it: stop() fails leftovers
        batcher._pump_task.cancel()
        try:
            await batcher._pump_task
        except asyncio.CancelledError:
            pass
        results = await asyncio.gather(*tasks, return_exceptions=True)
        assert all(isinstance(r, Exception) for r in results)

    asyncio.run(run())
    assert cache.stats()["entries"] == 0
    assert cache.get_negative("k") is None


def test_keyed_churn_never_strands_waiters():
    """Regression: a submit landing between the primary future settling and
    its done-callback running sees `done()` and starts a successor flight
    for the same key — the settled flight's waiters must still be fanned
    out (the callback owns its own waiter list; re-reading the dict there
    stranded them forever and deadlocked the load loop)."""
    engine = FakeEngine()
    batcher = MicroBatcher(engine, max_delay_ms=0.5)

    async def run():
        async def worker(n):
            for i in range(60):
                out = await batcher.submit(_img(), key=f"hot-{i % 2}")
                assert out == DETS

        await asyncio.wait_for(
            asyncio.gather(*(worker(w) for w in range(8))), timeout=30
        )
        await batcher.stop()

    asyncio.run(run())
    assert batcher._keyed == {}


# --- result cache semantics ---------------------------------------------------


def test_eviction_under_concurrent_fill_respects_byte_budget():
    metrics = Metrics()
    cache = ResultCache(max_bytes=4096, metrics=metrics)

    def fill(base):
        for i in range(100):
            key = f"m|{base}-{i}|t0.50"
            cache.put(key, [dict(DETS[0], score=float(i))])
            cache.get(key)

    with ThreadPoolExecutor(max_workers=8) as pool:
        list(pool.map(fill, range(8)))

    stats = cache.stats()
    assert 0 < stats["bytes"] <= 4096
    assert stats["entries"] > 0
    snap = metrics.snapshot()
    assert snap["cache_evictions_total"] > 0
    assert snap["cache_bytes"] <= 4096


def test_ttl_expiry_and_copy_semantics():
    now = [1000.0]
    cache = ResultCache(max_bytes=1 << 20, ttl_s=10.0, clock=lambda: now[0])
    cache.put("k", DETS)
    hit = cache.get("k")
    assert hit == DETS
    hit[0]["label"] = "mutated"  # a caller's mutation must not poison the cache
    assert cache.get("k")[0]["label"] == "tv"
    now[0] += 11.0
    assert cache.get("k") is None  # TTL expired


def test_oversized_value_not_stored():
    cache = ResultCache(max_bytes=64)
    cache.put("k", [dict(DETS[0], label="x" * 500)])
    assert cache.get("k") is None
    assert cache.stats()["bytes"] == 0


def test_negative_cache_ttl_expiry_reattempts_fetch():
    engine = FakeEngine()
    now = [0.0]
    cache = ResultCache(
        max_bytes=1 << 20,
        negative_ttl_s=5.0,
        metrics=engine.metrics,
        clock=lambda: now[0],
    )

    def not_found(url):
        resp = httpx.Response(404, request=httpx.Request("GET", url))
        return resp

    client = CountingClient(fail_with=not_found)
    det = _detector(engine, client, cache=cache)
    url = "http://cdn/missing.jpg"

    async def run():
        r1 = await det.detect({"image_urls": [url]})
        assert "HTTP Error" in r1.images[0].error
        assert client.fetches[url] == 1  # 404 fails fast, no retries
        r2 = await det.detect({"image_urls": [url]})
        assert "HTTP Error" in r2.images[0].error
        assert client.fetches[url] == 1  # negative hit: no second fetch
        now[0] += 6.0  # past the negative TTL
        r3 = await det.detect({"image_urls": [url]})
        assert "HTTP Error" in r3.images[0].error
        assert client.fetches[url] == 2  # expiry really re-attempted
        await det.aclose()

    asyncio.run(run())
    assert engine.metrics.snapshot()["cache_negative_hits_total"] == 1


def test_retryable_5xx_failures_never_cached():
    engine = FakeEngine()
    cache = _cache(engine)

    def server_error(url):
        return httpx.Response(500, request=httpx.Request("GET", url))

    client = CountingClient(fail_with=server_error)
    det = _detector(engine, client, cache=cache)
    url = "http://cdn/flaky.jpg"

    async def run():
        r1 = await det.detect({"image_urls": [url]})
        assert "HTTP Error" in r1.images[0].error
        fetches_first = client.fetches[url]
        assert fetches_first == 3  # full retry contract for retryable 5xx
        r2 = await det.detect({"image_urls": [url]})
        assert "HTTP Error" in r2.images[0].error
        assert client.fetches[url] == fetches_first * 2  # nothing was cached
        await det.aclose()

    asyncio.run(run())
    assert cache.get_negative(url_key(url)) is None
    assert engine.metrics.snapshot()["cache_negative_hits_total"] == 0


# --- disable switch + env knobs ----------------------------------------------


def test_cache_max_mb_zero_fully_disables_tier(monkeypatch):
    monkeypatch.setenv("SPOTTER_TPU_CACHE_MAX_MB", "0")
    engine = FakeEngine()
    client = CountingClient()
    batcher = MicroBatcher(engine, max_delay_ms=1.0)
    det = AmenitiesDetector(engine, batcher, client)
    assert det.cache is None
    assert batcher.result_cache is None

    async def run():
        # sequential duplicates: today's behavior is a fetch per request
        for _ in range(3):
            resp = await det.detect({"image_urls": ["http://cdn/a.jpg"]})
            assert isinstance(resp.images[0].detections, list)
        await det.aclose()

    asyncio.run(run())
    assert client.fetches == {"http://cdn/a.jpg": 3}
    assert sum(engine.calls) == 3
    assert batcher._keyed == {}
    snap = engine.metrics.snapshot()
    for counter in (
        "cache_hits_total",
        "cache_misses_total",
        "cache_negative_hits_total",
        "coalesced_fetches_total",
        "coalesced_submits_total",
        "cache_entries",
        "cache_bytes",
    ):
        assert snap[counter] == 0, counter


def test_from_env_knobs(monkeypatch):
    monkeypatch.delenv("SPOTTER_TPU_CACHE_MAX_MB", raising=False)
    assert ResultCache.from_env() is None  # off by default
    monkeypatch.setenv("SPOTTER_TPU_CACHE_MAX_MB", "8")
    monkeypatch.setenv("SPOTTER_TPU_CACHE_TTL_S", "120")
    monkeypatch.setenv("SPOTTER_TPU_CACHE_NEGATIVE_TTL_S", "7")
    cache = ResultCache.from_env()
    assert cache is not None
    assert cache.max_bytes == 8 * 1024 * 1024
    assert cache.ttl_s == 120.0
    assert cache.negative_ttl_s == 7.0
    # the explicit override (--cache-mb) wins over the env budget
    assert ResultCache.from_env(max_mb=0) is None
    assert ResultCache.from_env(max_mb=2).max_bytes == 2 * 1024 * 1024


def test_health_reports_cache_state():
    engine = FakeEngine()
    det = _detector(engine, cache=_cache(engine))
    health = det.health()
    assert health["cache"]["enabled"] is True
    assert health["cache"]["max_bytes"] == 1 << 20
    det_off = _detector(FakeEngine(), cache=None)
    assert det_off.health()["cache"] == {"enabled": False}


# --- chaos: faults on the cache path -----------------------------------------


def test_cache_faults_degrade_to_miss_never_fail_requests():
    engine = FakeEngine()
    det = _detector(engine, cache=_cache(engine))

    async def run():
        with faults.inject(cache_error=-1):  # every cache op raises
            for _ in range(2):
                resp = await det.detect({"image_urls": ["http://cdn/a.jpg"]})
                assert isinstance(resp.images[0].detections, list)
        await det.aclose()

    asyncio.run(run())
    # the cache never worked, so both requests paid the engine (miss path) —
    # and neither surfaced the injected failure
    assert sum(engine.calls) == 2
    assert engine.metrics.snapshot()["cache_hits_total"] == 0


# --- single-flight primitive --------------------------------------------------


def test_singleflight_failure_fans_to_every_waiter():
    calls = {"n": 0}

    async def run():
        flights = SingleFlight()

        async def boom():
            calls["n"] += 1
            await asyncio.sleep(0.02)
            raise ValueError("flight failed")

        results = await asyncio.gather(
            *(flights.run("k", boom) for _ in range(4)), return_exceptions=True
        )
        assert calls["n"] == 1
        assert all(isinstance(r, ValueError) for r in results)
        assert len({id(r) for r in results}) == 1

    asyncio.run(run())


def test_singleflight_waiter_cancellation_keeps_flight_alive():
    async def run():
        flights = SingleFlight()
        started = asyncio.Event()
        done = threading.Event()

        async def work():
            started.set()
            await asyncio.sleep(0.05)
            done.set()
            return 42

        t1 = asyncio.create_task(flights.run("k", work))
        await started.wait()
        t2 = asyncio.create_task(flights.run("k", work))
        await asyncio.sleep(0)
        t2.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t2
        assert await t1 == 42  # the shared flight survived t2's cancellation
        assert done.is_set()

    asyncio.run(run())
