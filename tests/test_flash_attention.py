"""Flash-attention cutover in MultiHeadAttention.

Long unmasked self-attention routes to the Pallas TPU flash kernel
(models/layers.py); the naive path materializes (B, H, S, S) scores, which
at ViT-detector sequence lengths (yolos-base: 4300 tokens) is HBM-bound by
~7 GB of scores per batch-8 forward. CPU keeps the naive fused-XLA path, so
the parity test against it runs on real TPU only.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spotter_tpu.models.layers import FLASH_ATTN_MIN_SEQ, MultiHeadAttention


def _mha_outputs(seq, backend_force_naive, seed=0):
    import spotter_tpu.models.layers as layers_mod

    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((2, seq, 64)), jnp.float32)
    pos = jnp.asarray(rng.standard_normal((1, seq, 64)), jnp.float32)
    mha = MultiHeadAttention(embed_dim=64, num_heads=4)
    params = mha.init(jax.random.PRNGKey(0), x, pos)

    if backend_force_naive:
        orig = layers_mod._FLASH_ATTN_ENABLED
        layers_mod._FLASH_ATTN_ENABLED = False
        try:
            return jax.jit(lambda p, a, b: mha.apply(p, a, b))(params, x, pos)
        finally:
            layers_mod._FLASH_ATTN_ENABLED = orig
    return jax.jit(lambda p, a, b: mha.apply(p, a, b))(params, x, pos)


def test_short_sequences_never_use_flash():
    """AIFI/decoder-length sequences stay on the reference path everywhere."""
    assert 400 < FLASH_ATTN_MIN_SEQ  # AIFI stride-32 tokens
    assert 300 < FLASH_ATTN_MIN_SEQ  # decoder queries


@pytest.mark.tpu
def test_flash_matches_naive_on_tpu():
    """Flash and naive self-attention agree on hardware (incl. the padded
    tail: 1100 tokens pad to 1536 in the kernel, segment ids isolate them)."""
    if jax.default_backend() != "tpu":
        pytest.skip("needs a TPU backend")
    seq = FLASH_ATTN_MIN_SEQ + 76  # non-multiple of the flash block
    flash = np.asarray(_mha_outputs(seq, backend_force_naive=False))
    naive = np.asarray(_mha_outputs(seq, backend_force_naive=True))
    np.testing.assert_allclose(flash, naive, atol=2e-5, rtol=2e-5)


def test_splash_interpret_matches_naive_on_cpu():
    """The splash backend's padding / segment-id / block-size plumbing runs
    on CPU via interpret mode (the msda-ops pattern), so a regression there
    surfaces in CI rather than only on hardware. 1100 tokens pads to 1536:
    a non-multiple of every block size, exercising the pad isolation.
    head_dim is 128 because the current jax splash kernel requires
    head_dim % NUM_LANES (128) == 0 — smaller heads (the original 8 here)
    raise NotImplementedError before the plumbing under test even runs."""
    from spotter_tpu.models.layers import _splash_self_attention

    rng = np.random.default_rng(0)
    b, s, h, hd = 1, 1100, 2, 128
    scale = hd**-0.5
    q = jnp.asarray(rng.standard_normal((b, s, h, hd)), jnp.float32) * scale
    k = jnp.asarray(rng.standard_normal((b, s, h, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, hd)), jnp.float32)

    got = _splash_self_attention(q, k, v, interpret=True)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k)
    weights = jax.nn.softmax(logits, axis=-1)
    want = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-3)


def test_splash_block_kv_policy():
    """The swept block_kv ladder (BASELINE.md rounds 3-4): 2304 when it
    divides the padded length (yolos 4608), full-row kv up to 3840
    (owlv2's 3601->3840: 10.18 vs 12.67 ms/layer at the old 768
    fallback), else the 768-multiple fallback."""
    from spotter_tpu.models.layers import _splash_block_kv

    assert _splash_block_kv(4608) == 2304
    assert _splash_block_kv(2304) == 2304
    assert _splash_block_kv(3840) == 3840  # owlv2: full-row kv
    assert _splash_block_kv(3072) == 3072
    assert _splash_block_kv(1536) == 1536
    assert _splash_block_kv(768) == 768
    assert _splash_block_kv(6144) == 1536  # >3840, not 2304-divisible
    assert _splash_block_kv(5376) == 768


def test_splash_block_q_policy():
    """Round-5 bq sweep: 512 at the >=4608 shapes it divides (yolos 4608:
    12.0 vs 13.6 ms/layer-attn), 384 elsewhere (3840 cannot take 512 —
    block_q must divide s_pad — and smaller shapes were swept at 384)."""
    from spotter_tpu.models.layers import _splash_block_q

    assert _splash_block_q(4608) == 512
    assert _splash_block_q(5120) == 512
    assert _splash_block_q(3840) == 384  # 512 does not divide
    assert _splash_block_q(3072) == 384  # below the measured 4608 scope
    assert _splash_block_q(768) == 384
    assert _splash_block_q(384) == 384
    assert _splash_block_q(4992) == 384  # >=4608 but 512 does not divide
