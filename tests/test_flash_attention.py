"""Flash-attention cutover in MultiHeadAttention.

Long unmasked self-attention routes to the Pallas TPU flash kernel
(models/layers.py); the naive path materializes (B, H, S, S) scores, which
at ViT-detector sequence lengths (yolos-base: 4300 tokens) is HBM-bound by
~7 GB of scores per batch-8 forward. CPU keeps the naive fused-XLA path, so
the parity test against it runs on real TPU only.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spotter_tpu.models.layers import FLASH_ATTN_MIN_SEQ, MultiHeadAttention


def _mha_outputs(seq, backend_force_naive, seed=0):
    import spotter_tpu.models.layers as layers_mod

    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((2, seq, 64)), jnp.float32)
    pos = jnp.asarray(rng.standard_normal((1, seq, 64)), jnp.float32)
    mha = MultiHeadAttention(embed_dim=64, num_heads=4)
    params = mha.init(jax.random.PRNGKey(0), x, pos)

    if backend_force_naive:
        orig = layers_mod._FLASH_ATTN_ENABLED
        layers_mod._FLASH_ATTN_ENABLED = False
        try:
            return jax.jit(lambda p, a, b: mha.apply(p, a, b))(params, x, pos)
        finally:
            layers_mod._FLASH_ATTN_ENABLED = orig
    return jax.jit(lambda p, a, b: mha.apply(p, a, b))(params, x, pos)


def test_short_sequences_never_use_flash():
    """AIFI/decoder-length sequences stay on the reference path everywhere."""
    assert 400 < FLASH_ATTN_MIN_SEQ  # AIFI stride-32 tokens
    assert 300 < FLASH_ATTN_MIN_SEQ  # decoder queries


@pytest.mark.tpu
def test_flash_matches_naive_on_tpu():
    """Flash and naive self-attention agree on hardware (incl. the padded
    tail: 1100 tokens pad to 1536 in the kernel, segment ids isolate them)."""
    if jax.default_backend() != "tpu":
        pytest.skip("needs a TPU backend")
    seq = FLASH_ATTN_MIN_SEQ + 76  # non-multiple of the flash block
    flash = np.asarray(_mha_outputs(seq, backend_force_naive=False))
    naive = np.asarray(_mha_outputs(seq, backend_force_naive=True))
    np.testing.assert_allclose(flash, naive, atol=2e-5, rtol=2e-5)
