"""Full-architecture torch-vs-Flax parity on the REAL RT-DETRv2-R101.

The locally-executable stand-in for the reference's golden-box integration
test (apps/spotter/tests/spotter/test_serve.py:246-326), which needs the
real checkpoint from the network: instantiate the real R101 HF architecture
random-init, convert through the PRODUCTION rules, and push the reference's
own fixture image through BOTH complete pipelines —

  torch:   HF image processor -> RTDetrV2ForObjectDetection ->
           post_process_object_detection
  spotter: preprocess_image -> InferenceEngine (bucketed jit forward +
           fixed-k postprocess) -> to_detections

— then require the same detections (labels equal, boxes within the golden
test's own ±1 px, scores within 2e-3). This executes the real param tree
through the converter, the real preprocess against HF's, and full-depth
numerics; nearly all of the golden-box risk dies here without the
checkpoint (VERDICT r3 next #2).

Runtime: several minutes of single-core CPU (torch R101 forward + one
XLA compile) — slow tier.
"""

import numpy as np
import pytest
from PIL import Image

torch = pytest.importorskip("torch")
from transformers import RTDetrImageProcessor, RTDetrResNetConfig, RTDetrV2Config
from transformers.models.rt_detr_v2.modeling_rt_detr_v2 import (
    RTDetrV2ForObjectDetection,
)

import jax

from spotter_tpu.convert.rtdetr_rules import rtdetr_rules
from spotter_tpu.convert.torch_to_jax import convert_state_dict
from spotter_tpu.engine.engine import BuiltDetector, InferenceEngine
from spotter_tpu.models.coco import coco_id2label_80
from spotter_tpu.models.configs import RTDETR_PRESETS, RTDetrConfig
from spotter_tpu.models.rtdetr import RTDetrDetector
from spotter_tpu.ops.preprocess import RTDETR_SPEC, preprocess_image

pytestmark = pytest.mark.slow

FIXTURE = "tests/test_data/test_pic.jpg"


def _real_r101_hf_config() -> RTDetrV2Config:
    """The published PekingU/rtdetr_v2_r101vd architecture (no network).

    initializer_range is widened (as in the tiny parity tests) so random-init
    encoder scores are distinct and torch/jax top-k select identical anchors;
    num_denoising=0 because denoising branches exist only in training.
    """
    backbone = RTDetrResNetConfig(
        embedding_size=64,
        hidden_sizes=[256, 512, 1024, 2048],
        depths=[3, 4, 23, 3],
        layer_type="bottleneck",
        out_features=["stage2", "stage3", "stage4"],
    )
    return RTDetrV2Config(
        backbone_config=backbone,
        d_model=256,
        encoder_hidden_dim=384,
        encoder_ffn_dim=2048,
        encoder_in_channels=[512, 1024, 2048],
        decoder_in_channels=[384, 384, 384],
        decoder_layers=6,
        num_queries=300,
        num_labels=80,
        num_denoising=0,
        initializer_range=0.2,
    )


def test_full_r101_pipeline_parity():
    hf_cfg = _real_r101_hf_config()
    cfg = RTDetrConfig.from_hf(hf_cfg)

    # the bench/serving preset IS this architecture (modulo label metadata)
    preset = RTDETR_PRESETS["rtdetr_v2_r101vd"]
    assert preset.backbone.depths == tuple(hf_cfg.backbone_config.depths)
    assert preset.d_model == cfg.d_model
    assert preset.encoder_hidden_dim == cfg.encoder_hidden_dim
    assert preset.encoder_ffn_dim == cfg.encoder_ffn_dim
    assert preset.decoder_layers == cfg.decoder_layers

    torch.manual_seed(0)
    model = RTDetrV2ForObjectDetection(hf_cfg).eval()
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, torch.nn.BatchNorm2d):
                m.running_mean.uniform_(-0.2, 0.2)
                m.running_var.uniform_(0.8, 1.2)

    params = convert_state_dict(model.state_dict(), rtdetr_rules(cfg), strict=False)

    image = Image.open(FIXTURE).convert("RGB")
    processor = RTDetrImageProcessor()

    # --- torch pipeline (the reference's serve.py flow, threshold aside)
    inputs = processor(images=image, return_tensors="pt")
    with torch.no_grad():
        tout = model(**inputs)
    t_sizes = torch.tensor([[image.height, image.width]])
    t_all = processor.post_process_object_detection(
        tout, threshold=0.0, target_sizes=t_sizes
    )[0]
    t_scores = t_all["scores"].numpy()
    # data-derived threshold: midpoint below the ~20th score, so both sides
    # select the same non-trivial set and a 1e-3 score wobble cannot flip
    # membership at the boundary
    kth = np.sort(t_scores)[::-1][20:22]
    threshold = float(kth.mean())
    t_res = processor.post_process_object_detection(
        tout, threshold=threshold, target_sizes=t_sizes
    )[0]
    t_dets = [
        {"label": coco_id2label_80()[int(l)], "score": float(s), "box": b.tolist()}
        for s, l, b in zip(t_res["scores"], t_res["labels"], t_res["boxes"])
    ]
    assert len(t_dets) >= 5, "threshold should keep a non-trivial set"

    # --- spotter pipeline: preprocess + engine + postprocess
    # preprocess parity on the very same call the engine will make
    arr, _, orig = preprocess_image(image, RTDETR_SPEC)
    np.testing.assert_allclose(
        arr, np.transpose(inputs["pixel_values"][0].numpy(), (1, 2, 0)), atol=1e-6
    )
    assert orig == (image.height, image.width)

    prev_precision = jax.config.jax_default_matmul_precision
    jax.config.update("jax_default_matmul_precision", "highest")
    try:
        built = BuiltDetector(
            model_name="parity/rtdetr_v2_r101vd",
            module=RTDetrDetector(cfg),
            params=params,
            preprocess_spec=RTDETR_SPEC,
            postprocess="sigmoid_topk",
            id2label=coco_id2label_80(),
            num_top_queries=cfg.num_queries,
        )
        engine = InferenceEngine(built, threshold=threshold, batch_buckets=(1,))
        j_dets = engine.detect([image])[0]
    finally:  # global jax config: restore so later tests keep their default
        jax.config.update("jax_default_matmul_precision", prev_precision)

    # --- same detections: greedy label+box matching, golden-test tolerances
    assert len(j_dets) == len(t_dets), (j_dets, t_dets)
    unmatched = list(range(len(j_dets)))
    for td in t_dets:
        best, best_d = None, np.inf
        for i in unmatched:
            jd = j_dets[i]
            if jd["label"] != td["label"]:
                continue
            d = max(abs(a - b) for a, b in zip(jd["box"], td["box"]))
            if d < best_d:
                best, best_d = i, d
        assert best is not None, f"no jax match for {td}"
        assert best_d <= 1.0, (td, j_dets[best], best_d)
        assert abs(j_dets[best]["score"] - td["score"]) <= 2e-3
        unmatched.remove(best)
