"""SPOTTER_TPU_S2D_STEM: space-to-depth stem conv is an exact rearrangement.

Same params, same input -> same backbone outputs as the plain path (up to
float reassociation), including the zero-padding edges. Fast tier: pure
jnp, tiny config, no torch.
"""

import jax
import numpy as np
import pytest

import spotter_tpu.models.resnet as resnet_mod
from spotter_tpu.models.configs import ResNetConfig
from spotter_tpu.models.resnet import ResNetBackbone

TINY_D = ResNetConfig(
    embedding_size=16,
    hidden_sizes=(16, 24, 32, 48),
    depths=(1, 1, 1, 1),
    layer_type="basic",
)


@pytest.mark.parametrize("hw", [(64, 64), (48, 80)])
def test_s2d_stem_matches_plain(monkeypatch, hw):
    h, w = hw
    x = np.random.default_rng(0).standard_normal((2, h, w, 3)).astype(np.float32)

    module = ResNetBackbone(TINY_D)
    monkeypatch.setattr(resnet_mod, "S2D_STEM", False)
    params = module.init(jax.random.PRNGKey(0), x[:1])["params"]
    ref = module.apply({"params": params}, x)

    monkeypatch.setattr(resnet_mod, "S2D_STEM", True)
    got = module.apply({"params": params}, x)

    assert len(ref) == len(got)
    for r, g in zip(ref, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=1e-5)


def test_s2d_param_tree_identical(monkeypatch):
    """Init under either flag yields the same param paths and shapes, so
    converted checkpoints load unchanged."""
    x = np.zeros((1, 64, 64, 3), np.float32)
    module = ResNetBackbone(TINY_D)
    monkeypatch.setattr(resnet_mod, "S2D_STEM", False)
    p_plain = module.init(jax.random.PRNGKey(0), x)["params"]
    monkeypatch.setattr(resnet_mod, "S2D_STEM", True)
    p_s2d = module.init(jax.random.PRNGKey(0), x)["params"]

    flat_plain = jax.tree_util.tree_map(lambda a: a.shape, p_plain)
    flat_s2d = jax.tree_util.tree_map(lambda a: a.shape, p_s2d)
    assert flat_plain == flat_s2d


def test_s2d_odd_input_falls_back(monkeypatch):
    """Odd spatial sizes use the plain conv (no shape errors)."""
    monkeypatch.setattr(resnet_mod, "S2D_STEM", True)
    x = np.zeros((1, 63, 65, 3), np.float32)
    module = ResNetBackbone(TINY_D)
    params = module.init(jax.random.PRNGKey(0), x)["params"]
    out = module.apply({"params": params}, x)
    assert out[0].shape[0] == 1
