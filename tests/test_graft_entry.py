"""The driver contract file must work on the virtual 8-device CPU mesh."""

import pytest

import sys

sys.path.insert(0, "/root/repo")

import __graft_entry__ as graft


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


# The default (real rtdetr_v2_r18vd preset) 8-device path is covered by
# tests/test_parallel.py::test_dryrun_real_r18_architecture_sharded; repeating
# it here would double the heaviest slow-tier compile. These cover the tiny
# smoke path (kept for fast driver/debug use) and the single-device fallback.
def test_dryrun_multichip_8_tiny():
    graft.dryrun_multichip(8, preset=None)


def test_dryrun_multichip_1_tiny():
    graft.dryrun_multichip(1, preset=None)


def test_dryrun_subprocess_reexec_forwards_default_preset(monkeypatch):
    """The driver-gate default path: when fewer devices are visible than
    requested, dryrun_multichip re-execs in a CPU subprocess and must forward
    the (string) preset and scrub the TPU-tunnel env. Mocked — the real
    subprocess execution is covered by the driver itself and by the direct
    path in test_parallel.py."""
    captured = {}

    def fake_run(cmd, **kwargs):
        captured["cmd"] = cmd
        captured["env"] = kwargs["env"]

        class R:
            returncode = 0
            stdout = "dryrun_multichip OK (mocked)\n"

        return R()

    monkeypatch.setattr(graft.subprocess, "run", fake_run)
    graft.dryrun_multichip(len(graft.jax.devices()) + 8)

    assert "preset='rtdetr_v2_r18vd'" in captured["cmd"][-1]
    env = captured["env"]
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=" in env["XLA_FLAGS"]
    for k in ("PJRT_LIBRARY_PATH", "PJRT_NAMES_AND_LIBRARY_PATHS", "PALLAS_AXON_POOL_IPS"):
        assert k not in env
