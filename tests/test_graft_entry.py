"""The driver contract file must work on the virtual 8-device CPU mesh."""

import pytest

import sys

sys.path.insert(0, "/root/repo")

import __graft_entry__ as graft


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_1():
    graft.dryrun_multichip(1)
