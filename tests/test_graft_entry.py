"""The driver contract file must work on the virtual 8-device CPU mesh."""

import sys

sys.path.insert(0, "/root/repo")

import __graft_entry__ as graft


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_1():
    graft.dryrun_multichip(1)
