"""Crash-safe control plane (ISSUE 16): the durable desired-state store
(CRC-framed journal + atomic-rename snapshot), leader-lease fencing, the
endpoints manifest, rollout resume planning, and the controller chaos
drills (testing/chaos_matrix.py::CONTROLLER_MATRIX) — a controller killed
-9 mid-rollout must be replaceable by a successor that adopts every live
member instead of double-spawning, resumes or rolls back the in-flight
wave, and reconverges desired == observed with zero client failures."""

import json
import os
import subprocess
import sys
import time

import pytest

from spotter_tpu.engine.metrics import ControlPlaneMetrics
from spotter_tpu.serving.reconcile import healthz_block, load_or_rebuild
from spotter_tpu.serving.rollout import resume_plan
from spotter_tpu.serving.statestore import (
    JOURNAL_NAME,
    SNAPSHOT_NAME,
    EndpointsManifest,
    LeaderLease,
    StaleLeaderError,
    StateCorruptError,
    StateStore,
    decode_records,
    encode_record,
    supervisor_alive,
)


def _seeded_store(directory: str) -> StateStore:
    """Snapshot + a live journal tail: the compaction-plus-appends shape a
    real controller leaves on disk."""
    store = StateStore.load(directory)
    store.set_pool("spot", size=3, version="v1", **{"class": "spot"})
    store.set_pool("serve", size=2, version="v1", **{"class": "on_demand"})
    store.compact()
    store.set_pool("spot", size=4)
    store.set_rollout({"state": "canary", "wave": 1, "version_to": "v2"})
    store.set_pool("serve", version="v2")
    return store


# ---------------------------------------------------------------------------
# desired-state store: fold, merge, compaction, sequence discipline


def test_store_roundtrip_merge_and_compaction(tmp_path):
    d = str(tmp_path / "state")
    store = _seeded_store(d)
    # set_pool merges over the existing spec: the size-only update must
    # not have dropped class/version
    assert store.state["pools"]["spot"] == {
        "class": "spot", "size": 4, "version": "v1",
    }
    assert store.state["pools"]["serve"]["version"] == "v2"
    assert store.state["rollout"]["state"] == "canary"

    again = StateStore.load(d)
    assert again.state == store.state
    assert again.seq == store.seq == 5
    assert again.journal_records == 3  # post-compaction appends only

    again.compact()
    assert StateStore.load(d).state == store.state
    assert os.path.getsize(os.path.join(d, JOURNAL_NAME)) == 0


def test_compaction_overlap_tail_is_skipped_not_corrupt(tmp_path):
    """Crash between compaction's two renames leaves snapshot(new) +
    journal(old tail): every tail seq is <= the snapshot seq, so load()
    skips them instead of double-applying or raising."""
    d = str(tmp_path / "state")
    store = _seeded_store(d)
    with open(os.path.join(d, JOURNAL_NAME), "rb") as f:
        old_tail = f.read()
    store.compact()
    with open(os.path.join(d, JOURNAL_NAME), "wb") as f:
        f.write(old_tail)
    again = StateStore.load(d)
    assert again.state == store.state
    assert again.journal_records == 0  # all skipped by seq


def test_sequence_gap_is_corruption(tmp_path):
    d = str(tmp_path / "state")
    os.makedirs(d)
    blob = encode_record({"op": "set_pool", "seq": 1, "name": "a",
                          "pool": {"size": 1}})
    blob += encode_record({"op": "set_pool", "seq": 3, "name": "a",
                           "pool": {"size": 2}})  # seq 2 lost
    with open(os.path.join(d, JOURNAL_NAME), "wb") as f:
        f.write(blob)
    with pytest.raises(StateCorruptError, match="sequence gap"):
        StateStore.load(d)


def test_unknown_op_and_snapshot_in_journal_are_corrupt(tmp_path):
    d = str(tmp_path / "state")
    os.makedirs(d)
    path = os.path.join(d, JOURNAL_NAME)
    with open(path, "wb") as f:
        f.write(encode_record({"op": "format_disk", "seq": 1}))
    with pytest.raises(StateCorruptError, match="unknown journal op"):
        StateStore.load(d)
    with open(path, "wb") as f:
        f.write(encode_record({"op": "remove_pool", "seq": 1, "name": "a"},
                              snapshot=True))
    with pytest.raises(StateCorruptError, match="snapshot record inside"):
        StateStore.load(d)


# ---------------------------------------------------------------------------
# the journal fuzz contract (satellite): damage is DETECTED, typed, and
# survivable — never silently replayed, never a crash loop


def _record_boundaries(blob: bytes, where: str) -> set[int]:
    """Offsets where a truncation leaves only whole records. Payloads are
    canonical JSON, so re-encoding reproduces the exact on-disk bytes."""
    offs, off = {0}, 0
    for flags, payload in decode_records(blob, where):
        off += len(encode_record(payload, snapshot=bool(flags & 0x01)))
        offs.add(off)
    return offs


def test_journal_fuzz_every_flip_and_truncation_is_typed(tmp_path):
    """The test_wire.py fuzz contract applied to the state files: every
    single-byte flip of snapshot or journal raises StateCorruptError, and
    every truncation either raises (mid-record: a torn write) or loads a
    strict prefix of the recorded intent (whole-record: byte-identical to
    fewer appends having happened — no framing can tell those apart, and
    reconciliation re-derives the lost tail from observation)."""
    d = str(tmp_path / "state")
    full = _seeded_store(d)
    jpath = os.path.join(d, JOURNAL_NAME)
    spath = os.path.join(d, SNAPSHOT_NAME)
    with open(jpath, "rb") as f:
        jblob = f.read()
    with open(spath, "rb") as f:
        sblob = f.read()

    def _restore():
        with open(jpath, "wb") as f:
            f.write(jblob)
        with open(spath, "wb") as f:
            f.write(sblob)

    try:
        # every truncation of the journal
        bounds = _record_boundaries(jblob, JOURNAL_NAME)
        for i in range(len(jblob) + 1):
            with open(jpath, "wb") as f:
                f.write(jblob[:i])
            if i in bounds:
                got = StateStore.load(d)
                assert got.seq <= full.seq
            else:
                with pytest.raises(StateCorruptError):
                    StateStore.load(d)
        # every single-byte flip of the journal
        with open(spath, "wb") as f:
            f.write(sblob)
        for i in range(len(jblob)):
            bad = bytearray(jblob)
            bad[i] ^= 0xFF
            with open(jpath, "wb") as f:
                f.write(bytes(bad))
            with pytest.raises(StateCorruptError):
                StateStore.load(d)
        # every single-byte flip of the snapshot
        with open(jpath, "wb") as f:
            f.write(jblob)
        for i in range(len(sblob)):
            bad = bytearray(sblob)
            bad[i] ^= 0xFF
            with open(spath, "wb") as f:
                f.write(bytes(bad))
            with pytest.raises(StateCorruptError):
                StateStore.load(d)
        # every mid-record truncation of the snapshot (its only whole-record
        # prefixes are empty and complete)
        sbounds = _record_boundaries(sblob, SNAPSHOT_NAME)
        assert sbounds == {0, len(sblob)}
        for i in range(1, len(sblob)):
            with open(spath, "wb") as f:
                f.write(sblob[:i])
            with pytest.raises(StateCorruptError):
                StateStore.load(d)
    finally:
        _restore()
    assert StateStore.load(d).state == full.state  # intact files still load


def test_load_or_rebuild_counts_and_quarantines_never_crash_loops(tmp_path):
    d = str(tmp_path / "state")
    _seeded_store(d)
    jpath = os.path.join(d, JOURNAL_NAME)
    with open(jpath, "r+b") as f:
        blob = bytearray(f.read())
        blob[len(blob) // 2] ^= 0xFF
        f.seek(0)
        f.write(bytes(blob))

    metrics = ControlPlaneMetrics()
    store = load_or_rebuild(d, metrics)
    assert metrics.journal_rebuilds_total == 1
    assert store.state == {"pools": {}, "rollout": None}  # rebuild, no replay
    # damaged intent is quarantined for the post-mortem, not deleted
    assert os.path.exists(jpath + ".corrupt")
    assert not os.path.exists(jpath)
    # the rebuilt store is immediately writable and the NEXT load is clean:
    # detection is a one-time event, not a crash loop
    store.set_pool("spot", size=1)
    again = load_or_rebuild(d, metrics)
    assert metrics.journal_rebuilds_total == 1
    assert again.state["pools"]["spot"]["size"] == 1


# ---------------------------------------------------------------------------
# leader lease: monotonic fencing epochs


def test_lease_takeover_bumps_epoch_and_fences_the_deposed(tmp_path):
    path = str(tmp_path / "leader.lease")
    a = LeaderLease(path, "A", ttl_s=10.0)
    b = LeaderLease(path, "B", ttl_s=10.0)

    assert a.try_acquire(now=100.0) and a.epoch == 1
    assert a.try_acquire(now=105.0) and a.epoch == 1  # renewal keeps epoch
    assert not b.try_acquire(now=106.0)  # A's lease is live
    assert a.check() == 1

    assert b.try_acquire(now=120.0)  # A expired: takeover MUST bump
    assert b.epoch == 2
    with pytest.raises(StaleLeaderError):
        a.check()  # the deposed leader's actuations are refused
    assert not a.leading
    assert b.check() == 2

    # voluntary release lets the standby take over immediately, still fenced
    b.release()
    c = LeaderLease(path, "C", ttl_s=10.0)
    assert c.try_acquire(now=121.0) and c.epoch == 3
    with pytest.raises(StaleLeaderError):
        b.check()


def test_lease_self_takeover_after_pause_kills_own_old_epoch(tmp_path):
    """A paused-past-TTL leader re-acquiring its OWN stale lease must get a
    new epoch: another controller may have acted during the pause."""
    path = str(tmp_path / "leader.lease")
    a = LeaderLease(path, "A", ttl_s=5.0)
    assert a.try_acquire(now=100.0) and a.epoch == 1
    assert a.try_acquire(now=200.0)  # own lease, long expired
    assert a.epoch == 2


def test_never_led_check_raises(tmp_path):
    lease = LeaderLease(str(tmp_path / "leader.lease"), "standby")
    with pytest.raises(StaleLeaderError):
        lease.check()


# ---------------------------------------------------------------------------
# endpoints manifest + liveness probe


def test_manifest_upsert_merge_and_remove(tmp_path):
    m = EndpointsManifest(str(tmp_path / "endpoints.json"))
    assert m.entries() == {}  # absent file = empty, never an error
    m.add("http://127.0.0.1:1", pool="spot", version="v1", supervisor_pid=7)
    m.add("http://127.0.0.1:1", supervisor_pid=8)  # restart re-registers
    m.add("http://127.0.0.1:2", pool="serve")
    entries = m.entries()
    assert entries["http://127.0.0.1:1"] == {
        "pool": "spot", "version": "v1", "supervisor_pid": 8,
    }
    m.remove("http://127.0.0.1:1")
    m.remove("http://127.0.0.1:1")  # idempotent
    assert list(m.entries()) == ["http://127.0.0.1:2"]


def test_manifest_garbage_file_reads_as_empty(tmp_path):
    path = tmp_path / "endpoints.json"
    path.write_text("{not json")
    m = EndpointsManifest(str(path))
    assert m.entries() == {}
    m.add("http://127.0.0.1:1", pool="spot")  # and is rebuilt by the next add
    assert list(m.entries()) == ["http://127.0.0.1:1"]


def test_supervisor_alive_rejects_dead_and_zombie_pids():
    assert supervisor_alive(os.getpid()) is True
    assert supervisor_alive(None) is False
    assert supervisor_alive(0) is False
    assert supervisor_alive(-5) is False

    # a zombie (exited, unreaped — exactly what a retired member's
    # supervisor becomes while its parent harness runs on) still answers
    # signal 0 but serves nothing: it must read as dead, or adoption would
    # adopt a corpse and shutdown would wait a full escalation timeout
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        try:
            with open(f"/proc/{proc.pid}/stat", "rb") as f:
                stat = f.read()
            if stat.rsplit(b")", 1)[-1].split()[0] == b"Z":
                break
        except OSError:
            break
        time.sleep(0.02)
    try:
        assert supervisor_alive(proc.pid) is False
    finally:
        proc.wait()  # reap
    assert supervisor_alive(proc.pid) is False  # fully gone


# ---------------------------------------------------------------------------
# rollout resume planning (tentpole part c, decision table)


def test_resume_plan_nothing_in_flight():
    assert resume_plan(None) is None
    assert resume_plan({"state": "done"}) is None
    assert resume_plan({"state": "rolled_back"}) is None
    assert resume_plan({"state": "idle"}) is None


def test_resume_plan_live_window_resumes_with_remainder():
    plan = resume_plan(
        {"state": "canary", "wave": 1, "canary_url": "http://c:1",
         "version_to": "v2", "window_s": 8.0, "window_deadline": 1005.0},
        now=1000.0,
    )
    assert plan["action"] == "resume"
    assert plan["canary_url"] == "http://c:1"
    assert plan["window_s"] == pytest.approx(5.0)  # remainder, not fresh


def test_resume_plan_expired_window_rolls_back():
    plan = resume_plan(
        {"state": "canary", "canary_url": "http://c:1",
         "window_deadline": 999.0},
        now=1000.0,
    )
    assert plan["action"] == "rollback"
    assert plan["reason"] == "verdict_window_expired"


def test_resume_plan_between_waves_restarts_the_wave():
    for state in ("spawning", "promoting"):
        plan = resume_plan(
            {"state": state, "wave": 2, "canary_url": "http://c:1"},
            now=1000.0,
        )
        assert plan["action"] == "restart_wave"
        assert plan["canary_url"] is None  # respawn/adopt, don't trust it


def test_healthz_block_none_safe():
    assert healthz_block(None) == {}


def test_fleet_top_renders_control_plane_drift():
    """fleet_top's control line (ISSUE 16 satellite): desired-vs-observed
    drift per pool from the `reconcile` block, absent (no phantom line)
    on edges without a control plane."""
    from tools.fleet_top import render

    fleet = {"replicas": {"up": 1, "seen": 1}, "per_replica": [],
             "slo_burn_rate": {}}
    out = render({
        "fleet": fleet,
        "reconcile": {
            "leader": True, "epoch": 3, "owner": "ctrl-b",
            "drift": {"spot": 1, "serve": 0},
            "drift_detail": {
                "spot": {"desired": 3, "ready": 2},
                "serve": {"desired": 2, "ready": 2},
            },
            "drift_total": 1, "converged": False,
            "adoptions_total": 5, "spawns_total": 1,
            "fencing_rejections_total": 0, "journal_rebuilds_total": 0,
        },
    })
    control = next(
        line for line in out.splitlines() if line.startswith("control:")
    )
    assert "leading epoch 3" in control
    assert "drift 1" in control
    assert "spot 2/3 ready" in control
    assert "serve 2/2 ready" in control
    assert "adopted 5" in control

    assert not any(
        line.startswith("control:")
        for line in render({"fleet": fleet}).splitlines()
    )


# ---------------------------------------------------------------------------
# the controller chaos drills (the acceptance surface): real subprocess
# controllers, kill -9 / SIGSTOP / journal corruption, successor adoption


@pytest.mark.slow
@pytest.mark.parametrize(
    "name",
    [
        "crash-mid-rollout-resume",
        "crash-expired-window-rollback",
        "crash-mid-storm",
        "journal-corrupt-rebuild",
        "stale-leader-fencing",
    ],
)
def test_controller_chaos_row(name, tmp_path):
    from spotter_tpu.testing.chaos_matrix import (
        CONTROLLER_MATRIX,
        run_controller_scenario,
    )

    sc = next(s for s in CONTROLLER_MATRIX if s.name == name)
    report = run_controller_scenario(sc, str(tmp_path))
    assert report["ok"], json.dumps(report, indent=2, default=str)
    if sc.converge_timeout_s:
        assert report["converge_s"] <= sc.converge_timeout_s
