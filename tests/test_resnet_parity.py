"""Numerical parity: Flax ResNet-D backbone vs HF torch RTDetrResNetBackbone.

The golden-accuracy anchor of the reference is torch-computed boxes
(tests/spotter/test_serve.py:293-300); parity at every stage is how we
guarantee the JAX path reproduces them. Uses tiny random-init configs — no
network, no pretrained weights.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import RTDetrResNetConfig
from transformers.models.rt_detr.modeling_rt_detr_resnet import RTDetrResNetBackbone

from spotter_tpu.convert import convert_state_dict, resnet_rules
from spotter_tpu.models.configs import ResNetConfig
from spotter_tpu.models.resnet import ResNetBackbone


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def _run_parity(layer_type: str, depths, hidden_sizes, embedding_size=16):
    hf_cfg = RTDetrResNetConfig(
        embedding_size=embedding_size,
        hidden_sizes=list(hidden_sizes),
        depths=list(depths),
        layer_type=layer_type,
        out_features=["stage1", "stage2", "stage3", "stage4"],
    )
    torch.manual_seed(0)
    model = RTDetrResNetBackbone(hf_cfg).eval()
    # randomize BN stats so parity actually exercises them
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, torch.nn.BatchNorm2d):
                m.running_mean.uniform_(-0.5, 0.5)
                m.running_var.uniform_(0.5, 1.5)

    cfg = ResNetConfig(
        embedding_size=embedding_size,
        hidden_sizes=tuple(hidden_sizes),
        depths=tuple(depths),
        layer_type=layer_type,
        out_indices=(1, 2, 3, 4),
    )
    params = convert_state_dict(
        model.state_dict(), resnet_rules(cfg, (), "")
    )

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 3, 64, 64)).astype(np.float32)

    with torch.no_grad():
        torch_feats = model(torch.from_numpy(x)).feature_maps

    flax_model = ResNetBackbone(cfg)
    jax_feats = flax_model.apply({"params": params}, np.transpose(x, (0, 2, 3, 1)))

    assert len(torch_feats) == len(jax_feats)
    for tf, jf in zip(torch_feats, jax_feats):
        tf = tf.numpy()
        jf = np.transpose(np.asarray(jf), (0, 3, 1, 2))
        assert tf.shape == jf.shape
        np.testing.assert_allclose(tf, jf, atol=2e-4, rtol=1e-3)


def test_basic_backbone_parity():
    _run_parity("basic", depths=(2, 2, 2, 2), hidden_sizes=(16, 24, 32, 48))


def test_bottleneck_backbone_parity():
    _run_parity("bottleneck", depths=(1, 2, 2, 1), hidden_sizes=(16, 32, 64, 128))
