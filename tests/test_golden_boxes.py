"""Golden-box integration test on the REAL RT-DETRv2-R101 checkpoint.

The reference's only end-to-end accuracy anchor (test_serve.py:246-326): run
the full detector pipeline on the checked-in fixture image
(tests/test_data/test_pic.jpg, the reference's own fixture — goldens are
defined against exactly these pixels) and assert the exact amenity set
{kitchen, oven, chair} with per-label boxes within ±1.0 px of the reference's
golden values. Passing this on the converted Flax checkpoint turns the
repo's "±1 px" docstring claims from extrapolation into tested fact.

Needs the real `PekingU/rtdetr_v2_r101vd` weights: marked integration +
network + slow, and skips cleanly when HF is unreachable and no local cache
exists (this build box has zero egress).
"""

import asyncio
import os
from pathlib import Path
from unittest.mock import AsyncMock

import numpy as np
import pytest

MODEL_NAME = "PekingU/rtdetr_v2_r101vd"
IMAGE = Path(__file__).parent / "test_data" / "test_pic.jpg"

# Reference golden outputs (test_serve.py:293-300): amenity set and
# [xmin, ymin, xmax, ymax] per label, tolerance abs=1.0 px.
GOLDEN = {
    "kitchen": [305.8487, 331.8141, 352.8352, 360.6238],
    "oven": [265.7876, 368.4354, 362.2969, 505.2321],
    "chair": [587.5251, 441.0653, 796.3880, 714.2424],
}

pytestmark = [pytest.mark.integration, pytest.mark.network, pytest.mark.slow]


def _build_real_detector(monkeypatch):
    """Real-weight build; skip (not fail) when weights are unreachable."""
    # other test modules export SPOTTER_TPU_TINY at import; this test is
    # about the REAL checkpoint, so scrub it for the build
    monkeypatch.delenv("SPOTTER_TPU_TINY", raising=False)
    from spotter_tpu.models import build_detector

    # Skip ONLY on fetch/cache unavailability: a conversion or model bug must
    # FAIL here, not silently skip the repo's one end-to-end accuracy anchor.
    unavailable: tuple = (OSError,)
    try:
        import huggingface_hub.errors as hf_errors

        unavailable = (OSError, hf_errors.HfHubHTTPError, hf_errors.EntryNotFoundError,
                       hf_errors.LocalEntryNotFoundError)
    except ImportError:
        pass
    try:
        return build_detector(MODEL_NAME)
    except unavailable as exc:  # HF hub unreachable / no cache (zero-egress box)
        pytest.skip(f"real checkpoint unavailable offline: {type(exc).__name__}: {exc}")


def _detect(built):
    from spotter_tpu.engine.batcher import MicroBatcher
    from spotter_tpu.engine.engine import InferenceEngine
    from spotter_tpu.serving.detector import AmenitiesDetector

    engine = InferenceEngine(built, threshold=0.5, batch_buckets=(1,))
    resp_ok = AsyncMock()
    resp_ok.content = IMAGE.read_bytes()
    resp_ok.raise_for_status = lambda: None
    client = AsyncMock()
    client.get.return_value = resp_ok
    detector = AmenitiesDetector(engine, MicroBatcher(engine, max_delay_ms=1.0), client)
    return asyncio.run(detector.detect({"image_urls": ["local://test_pic.jpg"]}))


def _assert_golden(result):
    from spotter_tpu.schemas import DetectionSuccessResult

    (image_result,) = result.images
    assert isinstance(image_result, DetectionSuccessResult), image_result
    assert len(image_result.labeled_image_base64) > 500
    detected = {d.label for d in image_result.detections}
    assert detected == set(GOLDEN), detected
    matched = set()
    for det in image_result.detections:
        want = GOLDEN.get(det.label)
        if want is not None and det.box == pytest.approx(want, abs=1.0):
            matched.add(det.label)
    assert matched == set(GOLDEN), (matched, image_result.detections)
    return {d.label: d.box for d in image_result.detections}


def _write_evidence(boxes: dict) -> None:
    """Committable run record (VERDICT r4 next #5): every successful golden
    run leaves `evidence/golden_r101.json` — boxes, per-coordinate deltas
    against the reference goldens, and the package versions that produced
    them. CI uploads it; a run on any egress-connected box can commit it.
    Controlled by SPOTTER_TPU_GOLDEN_EVIDENCE (default: repo evidence/)."""
    import datetime
    import importlib.metadata as md
    import json

    out = Path(
        os.environ.get(
            "SPOTTER_TPU_GOLDEN_EVIDENCE",
            Path(__file__).parent.parent / "evidence" / "golden_r101.json",
        )
    )
    out.parent.mkdir(parents=True, exist_ok=True)
    versions = {}
    for pkg in ("jax", "jaxlib", "flax", "torch", "transformers", "numpy", "pillow"):
        try:
            versions[pkg] = md.version(pkg)
        except md.PackageNotFoundError:
            versions[pkg] = None
    record = {
        "model": MODEL_NAME,
        "image": IMAGE.name,
        "timestamp_utc": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "int8": os.environ.get("SPOTTER_TPU_INT8", "0"),
        "platform": _jax_platform(),
        "versions": versions,
        "golden": GOLDEN,
        "measured": {k: [round(float(x), 4) for x in v] for k, v in boxes.items()},
        "max_abs_delta_px": round(
            max(
                abs(float(m) - float(g))
                for label in GOLDEN
                for m, g in zip(boxes[label], GOLDEN[label])
            ),
            4,
        ),
    }
    out.write_text(json.dumps(record, indent=2) + "\n")


def _jax_platform() -> str:
    import jax

    return jax.devices()[0].platform


def test_golden_boxes_real_checkpoint(tmp_path, monkeypatch):
    """Converted Flax R101 reproduces the reference's golden boxes, and the
    Orbax cache round-trip reproduces them identically."""
    from spotter_tpu.convert import loader

    monkeypatch.setenv(loader.CACHE_ENV, str(tmp_path / "cache"))
    built = _build_real_detector(monkeypatch)
    boxes_first = _assert_golden(_detect(built))
    _write_evidence(boxes_first)

    # Second build must hit the Orbax cache (no torch conversion) and the
    # cached params must reproduce bit-identical boxes.
    from spotter_tpu.models import build_detector

    built_cached = build_detector(MODEL_NAME)
    boxes_cached = _assert_golden(_detect(built_cached))
    for label, box in boxes_first.items():
        np.testing.assert_array_equal(np.asarray(box), np.asarray(boxes_cached[label]))
