"""Ragged mixed-resolution scheduling (ISSUE 9, engine/scheduler.py).

Three contracts, per the acceptance criteria:
- masked-region invariance: padding pixels NEVER change detections (the
  uint8 valid-dims substrate zeroes them before the model sees anything);
- mixed-bucket parity: a ragged (sub-bucket) canvas produces the same
  detections as the per-bucket reference within score/box tolerance (conv
  grid phase shifts at the canvas edge bound the residual);
- deadline-slack ordering: under a saturated queue an slo arrival enters
  the next dispatch ahead of older bulk work.

Plus the opt-out: with SPOTTER_TPU_RAGGED unset the scheduler is FIFO and
the engine is called without any canvas — the pre-ISSUE-9 behavior.
"""

import asyncio
import os
import threading
import time

import numpy as np
import pytest
from PIL import Image

os.environ["SPOTTER_TPU_TINY"] = "1"

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.engine.scheduler import QueueItem, Scheduler
from spotter_tpu.ops.preprocess import (
    PreprocessSpec,
    batch_images_uint8,
    decode_resize_uint8,
    ragged_canvas_supported,
    shortest_edge_size,
)
from spotter_tpu.serving.overload import BULK, SLO
from spotter_tpu.serving.resilience import Deadline

TINY_DETR_SPEC = PreprocessSpec(
    mode="shortest_edge", size=(48, 64),
    mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225), pad_to=(64, 64),
)


def _img(h, w, seed=0):
    rng = np.random.default_rng(seed)
    return Image.fromarray(rng.integers(0, 255, size=(h, w, 3), dtype=np.uint8))


def _item(h, w, cls=SLO, deadline=None, t=0.0):
    return QueueItem(
        image=_img(h, w), fut=None, deadline=deadline, t_submit=t, cls=cls
    )


# --- scheduler unit tests (pure, no engine) --------------------------------


def test_fifo_plan_is_arrival_order_and_drains_buffer():
    s = Scheduler(spec=TINY_DETR_SPEC, ragged=False)
    items = [_item(30, 40, t=i) for i in range(5)]
    buf = list(items)
    plan = s.plan(buf, 4)
    assert [id(i) for i in plan.items] == [id(i) for i in items[:4]]
    assert plan.canvas_hw is None  # FIFO never passes a canvas
    assert len(buf) == 1 and buf[0] is items[4]
    # waste is still measured against the static bucket (the baseline view)
    assert plan.padding_waste_pct is not None and plan.padding_waste_pct > 0


def test_ragged_pack_prefers_fit_over_growth():
    """Backfill takes same-shape items before growing the canvas: the big
    straggler waits one dispatch, the pack stays small."""
    s = Scheduler(spec=TINY_DETR_SPEC, ragged=True, step=16)
    # portrait images resize to (64, 48); the full-bucket one to (48, 64)
    small = [_item(80, 60, t=i) for i in range(3)]
    big = _item(60, 80, t=1.5)  # arrives between small[1] and small[2]
    buf = [small[0], small[1], big, small[2]]
    plan = s.plan(buf, 3)
    assert big not in plan.items  # displaced by the fitting backfill
    assert plan.items == [small[0], small[1], small[2]]
    assert plan.canvas_hw == (64, 48)
    assert buf == [big]
    # the straggler seeds the next pack
    plan2 = s.plan(buf, 3)
    assert plan2.items == [big] and plan2.canvas_hw == (48, 64)


def test_ragged_pack_grows_canvas_to_fill_target():
    """A dispatch costs padded_batch x canvas FLOPs whether its slots are
    full or not — with too few same-shape items the canvas grows rather
    than dispatching a runt pack."""
    s = Scheduler(spec=TINY_DETR_SPEC, ragged=True, step=16)
    buf = [_item(80, 60, t=0), _item(60, 80, t=1)]
    plan = s.plan(buf, 2)
    assert len(plan.items) == 2 and buf == []
    assert plan.canvas_hw == (64, 64)  # covers both aspects


def test_ragged_urgent_deadline_is_mandatory():
    """An item whose slack shrank below urgent_ms enters the pack even when
    it forces canvas growth — packing never displaces urgency."""
    s = Scheduler(spec=TINY_DETR_SPEC, ragged=True, step=16, urgent_ms=100.0)
    urgent = _item(60, 80, deadline=Deadline.after(0.05), t=5.0)
    relaxed = [_item(80, 60, t=i) for i in range(3)]
    buf = relaxed + [urgent]
    plan = s.plan(buf, 2)
    assert urgent in plan.items
    assert plan.canvas_hw == (64, 64)


def test_priority_orders_slo_before_bulk_then_slack():
    s = Scheduler(spec=TINY_DETR_SPEC, ragged=True)
    now = time.monotonic()
    bulk_old = _item(30, 40, cls=BULK, t=0.0)
    slo_loose = _item(30, 40, cls=SLO, deadline=Deadline.after(10.0), t=2.0)
    slo_tight = _item(30, 40, cls=SLO, deadline=Deadline.after(0.5), t=3.0)
    order = sorted(
        [bulk_old, slo_loose, slo_tight], key=lambda it: s.priority_key(it, now)
    )
    assert order == [slo_tight, slo_loose, bulk_old]


def test_canvas_snap_caps_at_static_bucket():
    s = Scheduler(spec=TINY_DETR_SPEC, ragged=True, step=48)
    assert s._snap((50, 50)) == (64, 64)  # 48 -> 96 capped at bucket 64
    assert s._snap((10, 10)) == (48, 48)


def test_fixed_spec_gets_slack_ordering_but_no_canvas():
    spec = PreprocessSpec(mode="fixed", size=(64, 64))
    assert not ragged_canvas_supported(spec)
    s = Scheduler(spec=spec, ragged=True)
    buf = [_item(30, 40, cls=BULK, t=0.0), _item(30, 40, cls=SLO, t=1.0)]
    plan = s.plan(buf, 2)
    assert plan.canvas_hw is None
    assert plan.items[0].cls == SLO  # ordering still applies


def test_too_small_canvas_fails_loudly():
    img = _img(80, 60)
    rh, rw = shortest_edge_size((80, 60), 48, 64)
    with pytest.raises(ValueError, match="cannot hold"):
        decode_resize_uint8(img, TINY_DETR_SPEC, canvas_hw=(rh - 8, rw))


# --- engine integration (tiny DETR, real jit on CPU) -----------------------


@pytest.fixture(scope="module")
def detr_engine():
    from spotter_tpu.engine.engine import InferenceEngine
    from spotter_tpu.models import build_detector

    built = build_detector("facebook/detr-resnet-50")
    return InferenceEngine(
        built, threshold=0.0, batch_buckets=(1, 2, 4), device_preprocess=True
    )


def test_masked_region_invariance(detr_engine):
    """Padding pixels never change detections: garbage bytes in the pad
    region of the staged uint8 batch produce BIT-IDENTICAL outputs (the
    in-jit mask zeroes them before the backbone sees anything)."""
    spec = detr_engine.built.preprocess_spec
    imgs = [_img(80, 60, seed=1), _img(40, 64, seed=2)]
    pixels, valid, sizes = batch_images_uint8(imgs, spec)
    garbage = pixels.copy()
    for j, img in enumerate(imgs):
        rh, rw = decode_resize_uint8(img, spec)[1]
        garbage[j, rh:, :] = 201
        garbage[j, :, rw:] = 77
    assert (garbage != pixels).any()
    clean = [np.asarray(o) for o in detr_engine._forward(
        detr_engine.params, pixels, valid, sizes
    )]
    dirty = [np.asarray(o) for o in detr_engine._forward(
        detr_engine.params, garbage, valid, sizes
    )]
    for a, b in zip(clean, dirty):
        np.testing.assert_array_equal(a, b)


def test_ragged_canvas_parity_vs_per_bucket_reference(detr_engine):
    """Mixed-bucket parity: detections from a ragged (sub-bucket) canvas
    match the per-bucket reference within score/box tolerance. The residual
    is conv grid phase at the canvas edge (stride arithmetic over 48 vs 64
    columns), bounded well below anything a staging bug (wrong mask, wrong
    normalize, wrong pad fill) would produce."""
    imgs = [_img(80, 60, seed=3), _img(96, 72, seed=4)]  # both -> (64, 48)
    full = detr_engine.detect(imgs)
    ragged = detr_engine.detect(imgs, canvas_hw=(64, 48))
    for a, b in zip(full, ragged):
        assert len(a) == len(b)
        sa = np.asarray([d["score"] for d in a], np.float32)
        sb = np.asarray([d["score"] for d in b], np.float32)
        # compare the score DISTRIBUTION sorted (rank flips between
        # near-equal random-init scores are not a staging bug)
        np.testing.assert_allclose(np.sort(sa), np.sort(sb), atol=0.12)
        ba = np.asarray([d["box"] for d in a], np.float32)
        bb = np.asarray([d["box"] for d in b], np.float32)
        assert float(np.abs(np.sort(ba, 0) - np.sort(bb, 0)).max()) < 6.0


def test_ragged_full_canvas_is_identical(detr_engine):
    """canvas == the static bucket stages byte-identical arrays, so the
    detections are bit-equal to the canvas-less call."""
    imgs = [_img(80, 60, seed=5)]
    a = detr_engine.detect(imgs)
    b = detr_engine.detect(imgs, canvas_hw=TINY_DETR_SPEC.pad_to)
    for da, db in zip(a[0], b[0]):
        assert da["label"] == db["label"]
        np.testing.assert_allclose(da["box"], db["box"], atol=1e-5)


# --- batcher integration ----------------------------------------------------


class RecordingEngine:
    """Synthetic engine: records every dispatch (image widths + canvas) and
    optionally blocks the first batch so a test can stack the queue."""

    def __init__(self, buckets=(2,), block_first=False):
        self.metrics = Metrics()
        self.batch_buckets = buckets
        self.batches: list[tuple[list[int], tuple | None]] = []
        self.release = threading.Event()
        self._block_first = block_first

    def detect(self, images, canvas_hw=None):
        first = not self.batches
        self.batches.append(([im.width for im in images], canvas_hw))
        if self._block_first and first:
            self.release.wait(5.0)
        return [[] for _ in images]


class PlainEngine:
    """Pre-ISSUE-9 signature: no canvas parameter. The batcher must detect
    this and never pass one, ragged or not."""

    def __init__(self):
        self.metrics = Metrics()
        self.batch_buckets = (4,)
        self.batches = []

    def detect(self, images):
        self.batches.append([im.width for im in images])
        return [[] for _ in images]


def test_deadline_slack_ordering_under_saturated_queue():
    """The acceptance scenario: the engine is busy, bulk work is queued,
    an slo request arrives late — the NEXT dispatch leads with the slo
    item, bulk backfills."""
    eng = RecordingEngine(buckets=(2,), block_first=True)
    batcher = MicroBatcher(
        eng, max_batch=2, max_delay_ms=20.0, max_in_flight=1, max_queue=0,
        scheduler=Scheduler(spec=None, ragged=True, step=8, urgent_ms=1e9),
    )

    async def drive():
        tasks = [
            asyncio.create_task(
                batcher.submit(_img(8, 16 + i), cls=BULK)
            )
            for i in range(4)
        ]
        await asyncio.sleep(0.15)  # first batch dispatched + blocked
        slo_task = asyncio.create_task(
            batcher.submit(
                _img(8, 96), deadline=Deadline.after(5.0), cls=SLO
            )
        )
        await asyncio.sleep(0.05)
        eng.release.set()
        await asyncio.gather(*tasks, slo_task)
        await batcher.stop()

    asyncio.run(drive())
    assert len(eng.batches) >= 2
    # the slo image (width 96) is in the dispatch right after the blocked
    # batch, ahead of bulk that arrived before it
    assert 96 in eng.batches[1][0]
    remaining_bulk = [w for ws, _ in eng.batches[1:] for w in ws if w != 96]
    assert remaining_bulk  # bulk still served (backfill, not starvation)


def test_ragged_off_is_fifo_and_never_passes_canvas(monkeypatch):
    monkeypatch.delenv("SPOTTER_TPU_RAGGED", raising=False)
    eng = PlainEngine()
    batcher = MicroBatcher(eng, max_batch=4, max_delay_ms=5.0)
    assert batcher.scheduler.fifo
    assert not batcher._engine_takes_canvas

    async def drive():
        await asyncio.gather(
            *(batcher.submit(_img(8, 10 + i)) for i in range(4))
        )
        await batcher.stop()

    asyncio.run(drive())
    assert all(sorted(ws) == ws for ws in eng.batches)  # arrival order


def test_ragged_env_arms_scheduler(monkeypatch):
    monkeypatch.setenv("SPOTTER_TPU_RAGGED", "1")
    eng = PlainEngine()
    batcher = MicroBatcher(eng, max_batch=4)
    assert batcher.scheduler.ragged
    # plain-signature engine still never sees a canvas
    assert not batcher._engine_takes_canvas


def test_padding_waste_and_slack_flow_to_metrics_and_prom():
    eng = RecordingEngine(buckets=(4,))
    batcher = MicroBatcher(
        eng, max_batch=4, max_delay_ms=5.0,
        scheduler=Scheduler(spec=None, ragged=True, step=8),
    )

    async def drive():
        await asyncio.gather(*(
            batcher.submit(
                _img(16, 16 * (1 + i % 2)), deadline=Deadline.after(5.0)
            )
            for i in range(8)
        ))
        await batcher.stop()

    asyncio.run(drive())
    snap = eng.metrics.snapshot()
    assert snap["ragged_packs_total"] >= 1
    assert snap["padding_waste_pct"] is not None
    assert snap["slack_at_dispatch_ms"]["p50"] > 0
    from spotter_tpu.obs import prom

    text = prom.render(snap)
    assert 'spotter_tpu_slack_at_dispatch_ms{quantile="0.5"}' in text
    assert "spotter_tpu_padding_waste_pct" in text
    assert "spotter_tpu_ragged_packs_total" in text


def test_ragged_batcher_end_to_end_with_real_engine(detr_engine):
    """Mixed-size images through MicroBatcher + the tiny DETR engine with
    the ragged scheduler armed: every request completes, packs use a
    ragged canvas, and per-request detection counts match a direct
    per-image reference call."""
    batcher = MicroBatcher(
        detr_engine, max_batch=4, max_delay_ms=20.0,
        scheduler=Scheduler(spec=TINY_DETR_SPEC, ragged=True, step=16),
    )
    sizes = [(80, 60), (96, 72), (80, 60), (40, 64)]
    imgs = [_img(h, w, seed=10 + i) for i, (h, w) in enumerate(sizes)]

    async def drive():
        results = await asyncio.gather(*(batcher.submit(img) for img in imgs))
        await batcher.stop()
        return results

    results = asyncio.run(drive())
    assert len(results) == 4
    for r in results:
        assert r and all({"label", "score", "box"} == set(d) for d in r)
    assert detr_engine.metrics.snapshot()["ragged_packs_total"] >= 1
