"""Edge data plane integration (ISSUE 11): binary wire format, X-Cache,
fleet-shared negative cache, affinity fan-out/fan-in, annotated-JPEG cache
entries — driven over real in-process HTTP (aiohttp test servers, model-free
synthetic engines, CPU-safe).
"""

import asyncio
import base64
import json
from io import BytesIO

import httpx
import numpy as np
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

from spotter_tpu.caching.result_cache import ResultCache
from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.serving import wire
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.replica_pool import ReplicaPool
from spotter_tpu.serving.router import make_router_app
from spotter_tpu.serving.standalone import make_app

URLS = [f"http://cdn.example.com/photo-{i}.jpg" for i in range(8)]
BAD_URL = "http://cdn.example.com/gone.jpg"


def _jpeg(seed: int, size: int = 48) -> bytes:
    rng = np.random.default_rng(seed)
    img = Image.fromarray(rng.integers(0, 255, (size, size, 3), dtype=np.uint8))
    buf = BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


BODIES = {u: _jpeg(i) for i, u in enumerate(URLS)}


class SyntheticEngine:
    def __init__(self) -> None:
        self.metrics = Metrics()
        self.batch_buckets = (8,)
        self.threshold = 0.5
        self.calls = 0

    def detect(self, images):
        self.calls += 1
        return [
            [{"label": "tv", "score": 0.9, "box": [1.0, 1.0, 9.0, 9.0]}]
            for _ in images
        ]


class CannedClient:
    def __init__(self, bodies: dict) -> None:
        self.bodies = bodies
        self.fetches = 0

    async def get(self, url: str):
        self.fetches += 1
        if url not in self.bodies:
            req = httpx.Request("GET", url)
            resp = httpx.Response(404, request=req)
            raise httpx.HTTPStatusError("404 Not Found", request=req, response=resp)
        body = self.bodies[url]

        class _Resp:
            content = body

            def raise_for_status(self):
                pass

        return _Resp()

    async def aclose(self):
        pass


def build_replica(cache_mb: float = 8.0, annotated: bool = True):
    engine = SyntheticEngine()
    cache = (
        ResultCache(
            max_bytes=int(cache_mb * 1024 * 1024),
            metrics=engine.metrics,
            annotated=annotated,
        )
        if cache_mb > 0
        else None
    )
    det = AmenitiesDetector(
        engine,
        MicroBatcher(engine, max_batch=8, max_delay_ms=1.0),
        CannedClient(dict(BODIES)),
        cache=cache,
    )
    return det, make_app(detector=det)


# -- frame unit tests --------------------------------------------------------


def _sample_body(degraded=None) -> dict:
    body = {
        "amenities_description": "The property contains: TV.",
        "images": [
            {
                "url": URLS[0],
                "detections": [{"label": "TV", "box": [1.0, 2.0, 3.0, 4.0]}],
                "labeled_image_base64": base64.b64encode(_jpeg(0)).decode(),
            },
            {"url": BAD_URL, "error": "Fetch Error: nope"},
        ],
    }
    if degraded is not None:
        body["degraded"] = degraded
    return body


def test_frame_roundtrip_and_layout():
    body = _sample_body(degraded=["stale"])
    frame = wire.encode_frame(body)
    assert frame[:4] == wire.FRAME_MAGIC
    assert frame[4] == wire.FRAME_VERSION
    assert wire.decode_frame(frame) == body
    header, segments = wire.split_frame(frame)
    # one raw segment (the success image), error image carried inline
    assert len(segments) == 1 and segments[0] == _jpeg(0)
    assert header["images"][0]["image_segment"] == 0
    assert "labeled_image_base64" not in header["images"][0]
    assert wire.build_frame(header, segments) == frame
    # the frame strictly beats JSON+base64 on the wire
    assert len(frame) < len(wire.to_json_bytes(body))


def test_frame_rejects_garbage():
    import pytest

    for bad in (b"", b"XXXX" + b"\x00" * 20, wire.encode_frame(_sample_body())[:-3]):
        with pytest.raises(wire.FrameError):
            wire.decode_frame(bad)


# -- wire integrity: frame v2 checksums + fuzz (ISSUE 14) --------------------


def test_frame_v2_layout_and_v1_interop():
    """v2 is the default encoding (header + per-segment checksums); v1
    frames (SPOTTER_TPU_WIRE_CRC=0, or an old peer) still decode."""
    body = _sample_body(degraded=["stale"])
    v2 = wire.encode_frame(body)
    assert v2[4] == wire.FRAME_VERSION == 2
    assert wire.decode_frame(v2) == body
    header, segments = wire.strip_segments(body)
    v1 = wire.build_frame(header, segments, crc=False)
    assert v1[4] == wire.FRAME_VERSION_V1 == 1
    assert wire.decode_frame(v1) == body
    # the v2 integrity layer costs exactly 4 bytes + 4 per segment
    assert len(v2) == len(v1) + 4 + 4 * len(segments)


def test_frame_corruption_is_typed_never_garbage():
    """A flipped bit in a CRC-protected region must raise
    FrameCorruptError (a FrameError subclass), not decode to garbage."""
    import pytest

    frame = wire.encode_frame(_sample_body())
    # flip one byte in the segment region (the JPEG tail)
    bad = bytearray(frame)
    bad[-2] ^= 0xFF
    with pytest.raises(wire.FrameCorruptError):
        wire.decode_frame(bytes(bad))
    # and one in the header region (after the 20-byte preamble)
    bad = bytearray(frame)
    bad[24] ^= 0x01
    with pytest.raises(wire.FrameCorruptError):
        wire.decode_frame(bytes(bad))
    assert issubclass(wire.FrameCorruptError, wire.FrameError)
    # verify_frame (the pool validator body) raises the same way
    with pytest.raises(wire.FrameCorruptError):
        frame_bad = bytearray(frame)
        frame_bad[-1] ^= 0x40
        wire.verify_frame(bytes(frame_bad))
    wire.verify_frame(frame)  # intact frame passes silently


def test_frame_fuzz_truncation_and_bitflips_always_typed():
    """The fuzz contract (ISSUE 14 satellite): ANY truncation and ANY
    single-byte corruption of a valid frame raises FrameError (or its
    FrameCorruptError subclass) — never struct.error, KeyError,
    UnicodeDecodeError, or a silent garbage decode. Exhaustive over every
    byte of a small frame plus seeded random multi-byte damage."""
    import random

    import pytest

    frame = wire.encode_frame(_sample_body(degraded=["stale"]))
    # every possible truncation
    for i in range(len(frame)):
        with pytest.raises(wire.FrameError):
            wire.decode_frame(frame[:i])
    # every single-byte flip: v2 checksums cover the preamble, header and
    # segments, so nothing slips through as a silent/garbage decode
    for i in range(len(frame)):
        bad = bytearray(frame)
        bad[i] ^= 0xFF
        with pytest.raises(wire.FrameError):
            wire.decode_frame(bytes(bad))
    # seeded random multi-byte damage (flips + slices + garbage splices)
    rng = random.Random(0xC0FFEE)
    for _ in range(300):
        bad = bytearray(frame)
        for _ in range(rng.randint(1, 8)):
            bad[rng.randrange(len(bad))] ^= 1 << rng.randrange(8)
        if rng.random() < 0.3:
            cut = rng.randrange(len(bad))
            bad = bad[:cut] + bytearray(rng.randbytes(rng.randint(0, 32)))
        try:
            wire.decode_frame(bytes(bad))
        except wire.FrameError:
            pass  # typed — the contract
        # any OTHER exception type propagates and fails the test


def test_corrupt_frame_fault_flips_a_checked_byte():
    """The chaos-matrix injection (faults.corrupt_frame_bytes) must damage
    a CRC-protected region: armed -> the frame fails validation exactly N
    times; unarmed -> identity."""
    import pytest

    from spotter_tpu.testing import faults

    frame = wire.encode_frame(_sample_body())
    assert faults.corrupt_frame_bytes(frame) == frame  # no plan: identity
    with faults.inject(corrupt_frame=2):
        first = faults.corrupt_frame_bytes(frame)
        second = faults.corrupt_frame_bytes(frame)
        third = faults.corrupt_frame_bytes(frame)  # armed count consumed
    assert first != frame and second != frame
    assert third == frame
    for bad in (first, second):
        with pytest.raises(wire.FrameCorruptError):
            wire.decode_frame(bad)


def test_negotiation_and_cache_summary():
    assert wire.wants_frame("application/x-spotter-frame")
    assert wire.wants_frame("application/json, application/x-spotter-frame;q=0.9")
    assert not wire.wants_frame("application/json")
    assert not wire.wants_frame(None)
    assert wire.summarize_cache_outcomes([]) is None
    assert wire.summarize_cache_outcomes(["hit", "hit"]) == "hit"
    assert wire.summarize_cache_outcomes(["hit", "negative"]) == "negative"
    assert wire.summarize_cache_outcomes(["hit", "coalesced"]) == "coalesced"
    assert wire.summarize_cache_outcomes(["hit", "miss"]) == "miss"


# -- annotated cache entries (ISSUE 11 satellite) ----------------------------


def test_annotated_entry_lifecycle():
    cache = ResultCache(max_bytes=1 << 20, annotated=True)
    raw = [{"label": "tv", "score": 0.9, "box": [1.0, 1.0, 9.0, 9.0]}]
    cache.put("k", raw)
    dets, stale, annotated = cache.get_entry_full("k")
    assert dets == raw and not stale and annotated is None
    jpeg = _jpeg(1)
    cache.attach_annotated("k", jpeg, [{"label": "TV", "box": [1.0, 1.0, 9.0, 9.0]}])
    dets, stale, annotated = cache.get_entry_full("k")
    assert annotated is not None and annotated["jpeg"] == jpeg
    assert cache.stats()["annotated_entries"] == 1
    # the sidecar's bytes count against the budget as ONE unit with the
    # entry: dropping the entry reclaims both
    bytes_with = cache.stats()["bytes"]
    assert bytes_with > len(jpeg)
    cache.put("k2", raw)  # refill elsewhere, then evict k by budget pressure
    cache.max_bytes = 200
    cache.put("k3", raw)
    assert cache.stats()["bytes"] <= max(200, 0) or cache.stats()["entries"] <= 1


def test_annotated_disabled_keeps_plain_entries():
    cache = ResultCache(max_bytes=1 << 20, annotated=False)
    cache.put("k", [{"label": "tv", "score": 0.9, "box": [1.0, 1.0, 9.0, 9.0]}])
    cache.attach_annotated("k", _jpeg(1), [])
    assert cache.get_entry_full("k")[2] is None


# -- replica HTTP surface ----------------------------------------------------


def test_replica_json_byte_identity_and_frame_negotiation():
    """The wire contract: not negotiated -> the JSON body is byte-identical
    to the pre-frame encoding (including exclude_none: no `degraded` key);
    negotiated -> the frame decodes to the same response."""

    async def run():
        det, app = build_replica()
        async with TestClient(TestServer(app)) as client:
            payload = {"image_urls": [URLS[0], BAD_URL]}
            resp = await client.post("/detect", json=payload)
            assert resp.status == 200
            raw = await resp.read()
            parsed = json.loads(raw)
            # byte-identity: the body IS the default json.dumps encoding of
            # the model dump (exactly what web.json_response(dump) emits)
            assert raw == json.dumps(parsed).encode()
            assert "degraded" not in parsed
            assert resp.headers[wire.X_CACHE_HEADER] == "miss"
            # the 404 produced a deterministic verdict header
            verdicts = wire.parse_negative_header(
                resp.headers.get(wire.NEGATIVE_HEADER)
            )
            assert [v["url"] for v in verdicts] == [BAD_URL]
            assert verdicts[0]["ttl_s"] > 0

            framed = await client.post(
                "/detect",
                json=payload,
                headers={"Accept": wire.FRAME_CONTENT_TYPE},
            )
            assert framed.status == 200
            assert framed.content_type == wire.FRAME_CONTENT_TYPE
            frame_raw = await framed.read()
            assert wire.decode_frame(frame_raw) == parsed
            assert len(frame_raw) < len(raw)
            # wire accounting on the replica
            snap = det.engine.metrics.snapshot()
            assert snap["wire_requests_total"] == 2
            assert snap["wire_frame_responses_total"] == 1
            assert snap["wire_json_responses_total"] == 1
            assert snap["wire_bytes_out_total"] == len(raw) + len(frame_raw)
        await det.aclose()

    asyncio.run(run())


def test_replica_x_cache_hit_and_annotated_fast_path():
    async def run():
        det, app = build_replica()
        async with TestClient(TestServer(app)) as client:
            payload = {"image_urls": [URLS[1]]}
            first = await client.post("/detect", json=payload)
            assert first.headers[wire.X_CACHE_HEADER] == "miss"
            second = await client.post("/detect", json=payload)
            assert second.headers[wire.X_CACHE_HEADER] == "hit"
            # hit responses are literally the same bytes (same annotated
            # JPEG, not a re-draw): the annotated sidecar served it
            assert (await first.read()) == (await second.read())
            assert det.cache.stats()["annotated_entries"] == 1
            assert det.engine.calls == 1  # the hit never reached the engine

            # second POST of the BAD url: served from the replica's own
            # negative cache
            await client.post("/detect", json={"image_urls": [BAD_URL]})
            neg = await client.post("/detect", json={"image_urls": [BAD_URL]})
            assert neg.headers[wire.X_CACHE_HEADER] == "negative"
            assert det.client.fetches == 3  # 2 images + 1 bad (cached after)
        await det.aclose()

    asyncio.run(run())


# -- router data plane -------------------------------------------------------


async def _start_fleet(n: int, **replica_kwargs):
    dets, servers, urls = [], [], []
    for _ in range(n):
        det, app = build_replica(**replica_kwargs)
        server = TestServer(app)
        await server.start_server()
        dets.append(det)
        servers.append(server)
        urls.append(f"http://{server.host}:{server.port}")
    return dets, servers, urls


async def _stop_fleet(dets, servers):
    for server in servers:
        await server.close()
    for det in dets:
        await det.aclose()


def test_router_affinity_fanout_reassembles_in_order():
    async def run():
        dets, servers, urls = await _start_fleet(3)
        pool = ReplicaPool(urls, health_interval_s=0.2)
        router_app = make_router_app(pool, affinity=True)
        async with TestClient(TestServer(router_app)) as client:
            payload = {"image_urls": list(URLS)}
            resp = await client.post("/detect", json=payload)
            assert resp.status == 200
            body = json.loads(await resp.read())
            assert [img["url"] for img in body["images"]] == list(URLS)
            assert body["amenities_description"] == "The property contains: TV."
            assert "degraded" not in body
            # run the same workload again: every URL must land on the
            # replica that cached it the first time — the affinity claim
            resp2 = await client.post("/detect", json=payload)
            assert resp2.headers[wire.X_CACHE_HEADER] == "hit"
            metrics = json.loads(await (await client.get("/metrics")).read())
            assert metrics["affinity"]["enabled"] is True
            assert metrics["affinity"]["routed_total"] >= 2
            assert metrics["affinity"]["hit_rate"] == 1.0
            assert metrics["affinity"]["ring_members"] == 3
            assert metrics["wire"]["bytes_out_total"] > 0
            assert metrics["wire"]["requests_total"] == 2
            # fleet-wide: the second pass was all hits, no new engine calls
            assert sum(d.engine.calls for d in dets) == len(URLS) or all(
                d.engine.calls <= len(URLS) for d in dets
            )
            hits = sum(
                d.engine.metrics.snapshot()["cache_hits_total"] for d in dets
            )
            assert hits == len(URLS)
        await _stop_fleet(dets, servers)

    asyncio.run(run())


def test_router_json_passthrough_byte_identity():
    """Single-owner requests pass the replica body through unchanged: the
    router adds NOTHING to the non-negotiated wire contract."""

    async def run():
        dets, servers, urls = await _start_fleet(2)
        pool = ReplicaPool(urls, health_interval_s=0.2)
        router_app = make_router_app(pool, affinity=True)
        async with TestClient(TestServer(router_app)) as client:
            payload = {"image_urls": [URLS[2]]}
            via_router = await (await client.post("/detect", json=payload)).read()
            # ask every replica directly; one of them served it
            direct_bodies = []
            async with httpx.AsyncClient() as hc:
                for u in urls:
                    r = await hc.post(f"{u}/detect", json=payload)
                    direct_bodies.append(r.content)
            assert via_router in direct_bodies
        await _stop_fleet(dets, servers)

    asyncio.run(run())


def test_router_frame_negotiation_and_merge():
    async def run():
        dets, servers, urls = await _start_fleet(3)
        pool = ReplicaPool(urls, health_interval_s=0.2)
        router_app = make_router_app(pool, affinity=True)
        async with TestClient(TestServer(router_app)) as client:
            payload = {"image_urls": list(URLS)}
            json_raw = await (await client.post("/detect", json=payload)).read()
            framed = await client.post(
                "/detect", json=payload,
                headers={"Accept": wire.FRAME_CONTENT_TYPE},
            )
            assert framed.content_type == wire.FRAME_CONTENT_TYPE
            frame_raw = await framed.read()
            assert wire.decode_frame(frame_raw) == json.loads(json_raw)
            # the ≥25% bytes-on-wire cut, observed at the client
            assert len(frame_raw) < 0.75 * len(json_raw), (
                f"frame {len(frame_raw)} vs json {len(json_raw)}"
            )
        await _stop_fleet(dets, servers)

    asyncio.run(run())


def test_router_edge_negative_cache_answers_without_replica():
    async def run():
        dets, servers, urls = await _start_fleet(2)
        pool = ReplicaPool(urls, health_interval_s=0.2)
        router_app = make_router_app(pool, affinity=True, edge_negative_ttl_s=30.0)
        async with TestClient(TestServer(router_app)) as client:
            payload = {"image_urls": [BAD_URL]}
            first = await client.post("/detect", json=payload)
            assert first.status == 200
            assert "error" in json.loads(await first.read())["images"][0]
            fetches_before = sum(d.client.fetches for d in dets)
            requests_before = pool.requests_total
            second = await client.post("/detect", json=payload)
            assert second.status == 200
            body = json.loads(await second.read())
            assert "error" in body["images"][0]
            assert body["images"][0]["url"] == BAD_URL
            assert second.headers[wire.X_CACHE_HEADER] == "negative"
            # the edge answered: zero replica work for the repeat
            assert sum(d.client.fetches for d in dets) == fetches_before
            assert pool.requests_total == requests_before
            metrics = json.loads(await (await client.get("/metrics")).read())
            assert metrics["edge_negative"]["hits_total"] == 1
            assert metrics["edge_negative"]["entries_added_total"] >= 1
        await _stop_fleet(dets, servers)

    asyncio.run(run())


def test_router_affinity_off_keeps_round_robin():
    async def run():
        dets, servers, urls = await _start_fleet(2)
        pool = ReplicaPool(urls, health_interval_s=0.2)
        router_app = make_router_app(pool, affinity=False)
        async with TestClient(TestServer(router_app)) as client:
            for _ in range(4):
                resp = await client.post(
                    "/detect", json={"image_urls": [URLS[0]]}
                )
                assert resp.status == 200
            metrics = json.loads(await (await client.get("/metrics")).read())
            assert metrics["affinity"]["enabled"] is False
            assert metrics["affinity"]["routed_total"] == 0
            # round-robin: BOTH replicas saw the same URL (the ~1/N decay
            # affinity exists to fix)
            assert all(d.client.fetches > 0 for d in dets)
        await _stop_fleet(dets, servers)

    asyncio.run(run())


def test_router_prometheus_exposition_carries_wire_gauges():
    async def run():
        dets, servers, urls = await _start_fleet(1)
        pool = ReplicaPool(urls, health_interval_s=0.2)
        router_app = make_router_app(pool, affinity=True)
        async with TestClient(TestServer(router_app)) as client:
            await client.post("/detect", json={"image_urls": [URLS[0]]})
            text = await (
                await client.get("/metrics?format=prometheus")
            ).text()
            for needle in (
                "spotter_tpu_wire_bytes_in_total",
                "spotter_tpu_wire_bytes_out_total",
                "spotter_tpu_affinity_hit_rate",
                "spotter_tpu_edge_negative_hits_total",
                "spotter_tpu_affinity_ring_members",
            ):
                assert needle in text, f"{needle} missing from exposition"
        await _stop_fleet(dets, servers)

    asyncio.run(run())
