"""Engine tests on the tiny RT-DETR (real jit path, CPU, no torch)."""

import asyncio
import os

import numpy as np
import pytest
from PIL import Image

os.environ["SPOTTER_TPU_TINY"] = "1"

from spotter_tpu.engine.engine import InferenceEngine
from spotter_tpu.models import build_detector


@pytest.fixture(scope="module")
def engine():
    built = build_detector("PekingU/rtdetr_v2_r101vd")
    return InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2, 4))


def _imgs(n, hw=(48, 64)):
    rng = np.random.default_rng(0)
    return [
        Image.fromarray(rng.integers(0, 255, size=(*hw, 3), dtype=np.uint8))
        for _ in range(n)
    ]


def test_detect_shapes_and_fields(engine):
    results = engine.detect(_imgs(2))
    assert len(results) == 2
    for dets in results:
        assert len(dets) > 0  # threshold 0 -> top-k all returned
        det = dets[0]
        assert set(det.keys()) == {"label", "score", "box"}
        assert len(det["box"]) == 4
        # boxes are scaled to original-image pixel coords (48x64 image):
        # cxcywh in (0,1) -> xyxy in (-w/2, 1.5w)
        xs = [d["box"][0] for d in dets] + [d["box"][2] for d in dets]
        assert -32.0 <= min(xs) and max(xs) <= 96.0


def test_batch_padding_strips_pad_results(engine):
    # 3 images -> bucket 4; must return exactly 3 results
    results = engine.detect(_imgs(3))
    assert len(results) == 3


def test_oversize_batch_splits(engine):
    results = engine.detect(_imgs(6))  # max bucket 4 -> two chunks
    assert len(results) == 6
    snap = engine.metrics.snapshot()
    assert snap["images_total"] >= 6
    assert snap["batches_total"] >= 2


def test_empty_detect_returns_empty(engine):
    assert engine.detect([]) == []


def test_pipelined_multichunk_matches_serial(engine):
    """detect()'s depth-2 pipeline returns the same per-image results, in
    order, as running each chunk through the serial path."""
    images = _imgs(9)  # 3 chunks at max bucket 4 (4+4+1)
    pipelined = engine.detect(images)
    serial = []
    for i in range(0, len(images), engine.batch_buckets[-1]):
        serial.extend(engine._detect_chunk(images[i : i + engine.batch_buckets[-1]]))
    assert len(pipelined) == len(serial) == 9
    for p, s in zip(pipelined, serial):
        assert [d["label"] for d in p] == [d["label"] for d in s]
        np.testing.assert_allclose(
            np.asarray([d["box"] for d in p], np.float32),
            np.asarray([d["box"] for d in s], np.float32),
            atol=1e-5,
        )


def test_device_preprocess_matches_host_path(engine):
    """SPOTTER_TPU_DEVICE_PREPROCESS's uint8 ingest (ISSUE 3) produces the
    same detections as the host float path, while shipping >=3.5x fewer H2D
    bytes/image (uint8 pixels + (B,2) valid vs float32 pixels + full mask)."""
    images = _imgs(5)
    dev = InferenceEngine(
        engine.built, threshold=0.0, batch_buckets=(1, 2, 4), device_preprocess=True
    )
    assert dev.device_preprocess
    a = engine.detect(images)
    b = dev.detect(images)
    assert len(a) == len(b) == 5
    for da, db in zip(a, b):
        assert [d["label"] for d in da] == [d["label"] for d in db]
        np.testing.assert_allclose(
            np.asarray([d["box"] for d in da], np.float32),
            np.asarray([d["box"] for d in db], np.float32),
            atol=1e-3,
        )
    host_bpi = engine.metrics.snapshot()["h2d_bytes_per_image"]
    dev_bpi = dev.metrics.snapshot()["h2d_bytes_per_image"]
    assert dev_bpi > 0 and host_bpi / dev_bpi >= 3.5


def test_device_preprocess_falls_back_for_pad_square():
    """OWLv2's pad_square spec can't defer its float warp to the device —
    the engine must quietly keep the host path rather than mis-normalize."""
    import dataclasses

    built = build_detector("PekingU/rtdetr_v2_r18vd")
    padded = dataclasses.replace(
        built, preprocess_spec=dataclasses.replace(
            built.preprocess_spec, mode="pad_square"
        )
    )
    eng = InferenceEngine(padded, threshold=0.0, batch_buckets=(1,),
                          device_preprocess=True)
    assert not eng.device_preprocess


def test_tiny_registry_model_name_matching():
    built = build_detector("PekingU/rtdetr_v2_r18vd")
    assert built.postprocess == "sigmoid_topk"
    assert built.id2label[62] == "tv"


def test_threshold_filters(engine):
    # with a high threshold the random model should return nothing
    high = InferenceEngine(engine.built, threshold=0.99, batch_buckets=(1,))
    results = high.detect(_imgs(1))
    assert results == [[]]


@pytest.mark.slow  # compile-heavy on 1-core CPU; full/CI run covers it
def test_detr_family_end_to_end():
    """Tiny DETR through the full engine path (shortest-edge + mask + softmax)."""
    built = build_detector("facebook/detr-resnet-50")
    assert built.postprocess == "softmax" and built.needs_mask
    eng = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2))
    results = eng.detect(_imgs(3, hw=(40, 72)))
    assert len(results) == 3
    for dets in results:
        assert all(set(d) == {"label", "score", "box"} for d in dets)


def test_yolos_family_end_to_end():
    """Tiny YOLOS through the full engine path (fixed warp + softmax)."""
    built = build_detector("hustvl/yolos-base")
    assert built.postprocess == "softmax" and not built.needs_mask
    eng = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2))
    results = eng.detect(_imgs(2, hw=(50, 70)))
    assert len(results) == 2
    assert all(len(d) > 0 for d in results)


@pytest.mark.slow  # compile-heavy on 1-core CPU; full/CI run covers it
def test_owlvit_family_end_to_end(monkeypatch):
    """Tiny OWL-ViT: cached text-query embeds ride apply_kwargs; labels come
    from the deploy-time query list, not checkpoint metadata."""
    monkeypatch.setenv("SPOTTER_TPU_TEXT_QUERIES", "tv,couch,bed")
    built = build_detector("google/owlvit-base-patch32")
    assert built.postprocess == "sigmoid_max"
    assert built.id2label == {0: "tv", 1: "couch", 2: "bed"}
    qe = built.apply_kwargs["query_embeds"]
    assert qe.shape == (3, 16)  # tiny projection_dim
    np.testing.assert_allclose(np.linalg.norm(qe, axis=-1), np.ones(3), atol=1e-5)
    eng = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2))
    results = eng.detect(_imgs(2, hw=(40, 40)))
    assert len(results) == 2
    labels = {d["label"] for dets in results for d in dets}
    assert labels <= {"tv", "couch", "bed"} and labels


@pytest.mark.slow  # compile-heavy on 1-core CPU; full/CI run covers it
def test_deformable_detr_family_end_to_end():
    """Tiny Deformable-DETR through the full engine path (shortest-edge +
    mask + sigmoid top-k)."""
    built = build_detector("SenseTime/deformable-detr-with-box-refine")
    assert built.postprocess == "sigmoid_topk" and built.needs_mask
    eng = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2))
    results = eng.detect(_imgs(3, hw=(40, 72)))
    assert len(results) == 3
    for dets in results:
        assert all(set(d) == {"label", "score", "box"} for d in dets)


def test_conditional_detr_registry_routing():
    """'conditional-detr-resnet-50' contains the 'detr-resnet' substring; the
    registry must route it to the conditional family (registration order)."""
    built = build_detector("microsoft/conditional-detr-resnet-50")
    assert built.postprocess == "sigmoid_topk"
    assert type(built.module).__name__ == "ConditionalDetrDetector"


def test_owlv2_registry_routing(monkeypatch):
    """owlv2 names resolve to the owlvit family (shared architecture)."""
    monkeypatch.setenv("SPOTTER_TPU_TEXT_QUERIES", "tv")
    built = build_detector("google/owlv2-base-patch16-ensemble")
    assert built.postprocess == "sigmoid_max"
    assert type(built.module).__name__ == "OwlViTDetector"


def test_dab_detr_registry_routing():
    """'dab-detr-resnet-50' contains 'detr-resnet'; must route to dab_detr."""
    built = build_detector("IDEA-Research/dab-detr-resnet-50")
    assert built.postprocess == "sigmoid_topk" and built.needs_mask
    assert type(built.module).__name__ == "DabDetrDetector"


@pytest.mark.slow  # compile-heavy on 1-core CPU; full/CI run covers it
def test_dab_detr_family_end_to_end():
    """Tiny DAB-DETR through the full engine path (shortest-edge + mask +
    sigmoid top-k)."""
    built = build_detector("IDEA-Research/dab-detr-resnet-50")
    eng = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2))
    results = eng.detect(_imgs(3, hw=(40, 72)))
    assert len(results) == 3
    for dets in results:
        assert all(set(d) == {"label", "score", "box"} for d in dets)


def test_host_float_path_emits_no_donation_warning():
    """ISSUE 5 satellite: only the uint8 staging buffer that
    device_rescale_normalize consumes is donated. The host-float path's
    float pixels can never alias the tiny postprocess outputs, so donating
    them freed nothing and warned "Some donated buffers were not usable:
    float32[...]" on every call (BENCH_r05 tail)."""
    import warnings

    built = build_detector("PekingU/rtdetr_v2_r101vd")
    eng = InferenceEngine(
        built, threshold=0.0, batch_buckets=(2,), device_preprocess=False
    )
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        results = eng.detect(_imgs(2))
    assert len(results) == 2
    donation = [
        w for w in caught if "donated buffers" in str(w.message).lower()
    ]
    assert donation == [], [str(w.message) for w in donation]
