"""Numerical parity: Flax DabDetrDetector vs HF torch DabDetrForObjectDetection.

Tiny random-init config, no network — covers the anchor-sine conditioning,
per-layer anchor refinement, PReLU FFNs, encoder pos rescaling, and the
padded-pixel-mask path."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import DabDetrConfig as HFDabDetrConfig
from transformers import ResNetConfig as HFResNetConfig
from transformers.models.dab_detr.modeling_dab_detr import DabDetrForObjectDetection

from spotter_tpu.convert.dab_detr_rules import dab_detr_rules
from spotter_tpu.convert.torch_to_jax import convert_state_dict
from spotter_tpu.models.configs import DabDetrConfig
from spotter_tpu.models.dab_detr import DabDetrDetector


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def _tiny_hf_config(**kw):
    backbone = HFResNetConfig(
        embedding_size=8,
        hidden_sizes=[8, 12, 16, 24],
        depths=[1, 1, 1, 1],
        layer_type="basic",
        out_features=["stage4"],
    )
    return HFDabDetrConfig(
        use_timm_backbone=False,
        use_pretrained_backbone=False,
        backbone=None,
        backbone_config=backbone,
        hidden_size=32,
        encoder_layers=2,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        num_queries=9,
        num_labels=7,
        **kw,
    )


def _run_parity(hf_cfg, with_mask: bool):
    torch.manual_seed(0)
    model = DabDetrForObjectDetection(hf_cfg).eval()
    with torch.no_grad():
        for m in model.modules():
            if hasattr(m, "running_mean"):
                m.running_mean.uniform_(-0.2, 0.2)
                m.running_var.uniform_(0.8, 1.2)

    cfg = DabDetrConfig.from_hf(hf_cfg)
    params = convert_state_dict(model.state_dict(), dab_detr_rules(cfg), strict=True)

    rng = np.random.default_rng(1)
    x = rng.uniform(0, 1, size=(2, 3, 64, 96)).astype(np.float32)
    if with_mask:
        mask = np.zeros((2, 64, 96), dtype=np.int64)
        mask[0, :64, :80] = 1
        mask[1, :48, :96] = 1
    else:
        mask = np.ones((2, 64, 96), dtype=np.int64)

    with torch.no_grad():
        tout = model(torch.from_numpy(x), pixel_mask=torch.from_numpy(mask))

    jout = DabDetrDetector(cfg).apply(
        {"params": params},
        np.transpose(x, (0, 2, 3, 1)),
        mask.astype(np.float32) if with_mask else None,
    )

    np.testing.assert_allclose(
        np.asarray(jout["pred_boxes"]), tout.pred_boxes.numpy(), atol=5e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(jout["logits"]), tout.logits.numpy(), atol=1e-3, rtol=1e-3
    )


def test_dab_detr_parity():
    _run_parity(_tiny_hf_config(), with_mask=False)


def test_dab_detr_parity_masked():
    _run_parity(_tiny_hf_config(), with_mask=True)


def test_dab_detr_parity_keep_query_pos():
    _run_parity(_tiny_hf_config(keep_query_pos=True), with_mask=False)
