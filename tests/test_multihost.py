"""Multi-host bring-up tests: env contract, mesh-spec knob, and a real
2-process jax.distributed smoke run on CPU.

The reference has no multi-host path at all (SURVEY.md §2.3 row multi-host
SPMD); this framework's is TPU_WORKER_* env -> jax.distributed.initialize
(parallel/multihost.py), exercised here the way the k8s orchestration is
exercised with a fake apiserver: two real local processes, no cluster.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

from spotter_tpu.parallel import initialize_multihost, multihost_env_summary
from spotter_tpu.serving.app import parse_mesh_spec

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_single_host_is_noop(monkeypatch):
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    monkeypatch.delenv("TPU_WORKER_ID", raising=False)
    assert initialize_multihost() is False
    with pytest.raises(RuntimeError):
        initialize_multihost(force=True)


def test_env_summary_contract(monkeypatch):
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    summary = multihost_env_summary()
    assert summary["TPU_WORKER_ID"] == "1"
    assert summary["TPU_WORKER_HOSTNAMES"] == "h0,h1"
    assert summary["SPOTTER_COORDINATOR_PORT"]  # always has a default


def test_parse_mesh_spec():
    assert parse_mesh_spec("dp=4") == {"dp": 4, "tp": 1}
    assert parse_mesh_spec("dp=4,tp=2") == {"dp": 4, "tp": 2}
    assert parse_mesh_spec(" dp=2 , tp=1 ") == {"dp": 2, "tp": 1}
    for bad in ("", "tp=2", "dp=0", "dp=x", "pp=2,dp=2"):
        with pytest.raises(ValueError):
            parse_mesh_spec(bad)


_WORKER_SCRIPT = textwrap.dedent(
    """
    import numpy as np
    from spotter_tpu.parallel import initialize_multihost

    assert initialize_multihost() is True
    import jax
    from jax.experimental import multihost_utils

    assert jax.process_count() == 2
    gathered = multihost_utils.process_allgather(
        np.array([jax.process_index()], np.int32)
    )
    assert sorted(int(v) for v in gathered.ravel()) == [0, 1], gathered
    print(f"worker {jax.process_index()} OK")
    """
)


@pytest.mark.slow
def test_two_process_distributed_smoke():
    """Two real processes join one jax.distributed cluster over localhost and
    run a cross-process allgather — the CPU stand-in for a 2-host DCN slice
    (VERDICT r1 item 4's 'done' criterion)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = []
    for worker_id in range(2):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            TPU_WORKER_ID=str(worker_id),
            TPU_WORKER_HOSTNAMES="127.0.0.1,127.0.0.1",
            SPOTTER_COORDINATOR_PORT=str(port),
            PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        # the virtual 8-device flag from conftest must not leak in: each
        # worker contributes its own (single-CPU-device) local runtime
        env["XLA_FLAGS"] = ""
        # no TPU-tunnel plugin in the workers: its sitecustomize bootstrap
        # (keyed off these vars) registers a PJRT plugin and its own
        # distributed context, which would shadow the 2-process cluster
        for var in (
            "PJRT_LIBRARY_PATH",
            "PJRT_NAMES_AND_LIBRARY_PATHS",
            "PALLAS_AXON_POOL_IPS",
        ):
            env.pop(var, None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER_SCRIPT],
                env=env,
                cwd=REPO_ROOT,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"worker {i} OK" in out
