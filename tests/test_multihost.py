"""Multi-host bring-up tests: env contract, mesh-spec knob, and a real
2-process jax.distributed smoke run on CPU.

The reference has no multi-host path at all (SURVEY.md §2.3 row multi-host
SPMD); this framework's is TPU_WORKER_* env -> jax.distributed.initialize
(parallel/multihost.py), exercised here the way the k8s orchestration is
exercised with a fake apiserver: two real local processes, no cluster.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

from spotter_tpu.parallel import initialize_multihost, multihost_env_summary
from spotter_tpu.serving.app import parse_mesh_spec

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_single_host_is_noop(monkeypatch):
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    monkeypatch.delenv("TPU_WORKER_ID", raising=False)
    assert initialize_multihost() is False
    with pytest.raises(RuntimeError):
        initialize_multihost(force=True)


def test_env_summary_contract(monkeypatch):
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    summary = multihost_env_summary()
    assert summary["TPU_WORKER_ID"] == "1"
    assert summary["TPU_WORKER_HOSTNAMES"] == "h0,h1"
    assert summary["SPOTTER_COORDINATOR_PORT"]  # always has a default


def test_parse_mesh_spec():
    assert parse_mesh_spec("dp=4") == {"dp": 4, "tp": 1}
    assert parse_mesh_spec("dp=4,tp=2") == {"dp": 4, "tp": 2}
    assert parse_mesh_spec(" dp=2 , tp=1 ") == {"dp": 2, "tp": 1}
    for bad in ("", "tp=2", "dp=0", "dp=x", "pp=2,dp=2"):
        with pytest.raises(ValueError):
            parse_mesh_spec(bad)


_WORKER_SCRIPT = textwrap.dedent(
    """
    import numpy as np
    from spotter_tpu.parallel import initialize_multihost

    assert initialize_multihost() is True
    import jax
    from jax.experimental import multihost_utils

    assert jax.process_count() == 2
    gathered = multihost_utils.process_allgather(
        np.array([jax.process_index()], np.int32)
    )
    assert sorted(int(v) for v in gathered.ravel()) == [0, 1], gathered
    print(f"worker {jax.process_index()} OK")
    """
)


@pytest.mark.slow
def test_two_process_distributed_smoke():
    """Two real processes join one jax.distributed cluster over localhost and
    run a cross-process allgather — the CPU stand-in for a 2-host DCN slice
    (VERDICT r1 item 4's 'done' criterion)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = []
    for worker_id in range(2):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            TPU_WORKER_ID=str(worker_id),
            TPU_WORKER_HOSTNAMES="127.0.0.1,127.0.0.1",
            SPOTTER_COORDINATOR_PORT=str(port),
            PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        # the virtual 8-device flag from conftest must not leak in: each
        # worker contributes its own (single-CPU-device) local runtime
        env["XLA_FLAGS"] = ""
        # no TPU-tunnel plugin in the workers: its sitecustomize bootstrap
        # (keyed off these vars) registers a PJRT plugin and its own
        # distributed context, which would shadow the 2-process cluster
        for var in (
            "PJRT_LIBRARY_PATH",
            "PJRT_NAMES_AND_LIBRARY_PATHS",
            "PALLAS_AXON_POOL_IPS",
        ):
            env.pop(var, None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER_SCRIPT],
                env=env,
                cwd=REPO_ROOT,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"worker {i} OK" in out


_MESH_SERVE_SCRIPT = textwrap.dedent(
    """
    import numpy as np
    from spotter_tpu.parallel import initialize_multihost

    assert initialize_multihost() is True
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from spotter_tpu.parallel.mesh import make_mesh
    from spotter_tpu.parallel.sharding import data_sharding, replicated
    from spotter_tpu.serving.app import parse_mesh_spec

    assert jax.process_count() == 2
    assert len(jax.devices()) == 2  # one CPU device contributed per process

    # the exact serving bring-up order (serving/app.py build_detector_app):
    # initialize_multihost BEFORE make_mesh, spec via parse_mesh_spec
    axes = parse_mesh_spec("dp=2")
    mesh = make_mesh(dp=axes["dp"], tp=axes["tp"], source="test")
    in_sharding = data_sharding(mesh)

    # per-process batch shard -> global dp-sharded batch, exactly how the
    # engine places a bucket over the mesh (engine._in_sharding)
    local = np.full((2, 8), float(jax.process_index() + 1), np.float32)
    batch = jax.make_array_from_process_local_data(in_sharding, local, (4, 8))
    w = jax.device_put(np.eye(8, dtype=np.float32), replicated(mesh))

    @jax.jit
    def head(x, w):
        return jnp.tanh(x @ w).sum(axis=1)

    out = head(batch, w)
    got = sorted(
        round(float(v), 5)
        for shard in out.addressable_shards
        for v in np.asarray(shard.data).ravel()
    )
    want = sorted(
        round(float(np.tanh(jax.process_index() + 1)) * 8, 5)
        for _ in range(2)
    )
    assert got == want, (got, want)
    # and the cross-process view agrees: 2 rows of tanh(1)*8, 2 of tanh(2)*8
    all_rows = sorted(
        round(float(v), 5)
        for v in multihost_utils.process_allgather(np.asarray(got)).ravel()
    )
    expect = sorted(
        round(float(np.tanh(p + 1)) * 8, 5) for p in (0, 0, 1, 1)
    )
    assert all_rows == expect, (all_rows, expect)
    print(f"worker {jax.process_index()} MESH-SERVE OK")
    """
)


@pytest.mark.slow
def test_two_process_dp_mesh_serving_dryrun():
    """VERDICT r5 item 7: `initialize_multihost` + dp-mesh serving exercised
    TOGETHER — two real jax.distributed processes build one global dp=2 mesh
    through the serving bring-up path (parse_mesh_spec -> make_mesh ->
    data_sharding/replicated placement) and run a jitted sharded forward
    over a batch assembled from process-local shards. The 8-device dryrun
    is single-process; this is the cross-process half of config #5."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = []
    for worker_id in range(2):
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            TPU_WORKER_ID=str(worker_id),
            TPU_WORKER_HOSTNAMES="127.0.0.1,127.0.0.1",
            SPOTTER_COORDINATOR_PORT=str(port),
            PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        env["XLA_FLAGS"] = ""  # one device per worker, no virtual mesh
        for var in (
            "PJRT_LIBRARY_PATH",
            "PJRT_NAMES_AND_LIBRARY_PATHS",
            "PALLAS_AXON_POOL_IPS",
        ):
            env.pop(var, None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _MESH_SERVE_SCRIPT],
                env=env,
                cwd=REPO_ROOT,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"worker {i} MESH-SERVE OK" in out
