"""ReplicaPool unit tests (ISSUE 2): health-aware selection, replay on
transport errors and replayable statuses, outlier ejection with exponential
backoff + health-loop recovery, hedging, and the counters snapshot. ISSUE 6
adds the retry budget (replays capped at a fraction of recent request rate),
the suspended-pool fast 503 (a fully-ejected/empty pool must not burn the
client's deadline), and dynamic membership. Replicas here are tiny
in-process aiohttp servers with scriptable behavior — the subprocess/chaos
version lives in tests/test_failover.py and tests/test_fleet.py."""

import asyncio
import time

import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from spotter_tpu.serving.replica_pool import (
    PoolExhaustedError,
    PoolSuspendedError,
    ReplicaPool,
    RetryBudget,
    RetryBudgetExhaustedError,
)

PAYLOAD = {"image_urls": ["http://example.com/room.jpg"]}


class ScriptedReplica:
    """In-process /detect + /healthz server whose behavior mutates mid-test."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.status = 200
        self.delay_s = 0.0
        self.health_status = 200
        self.detect_calls = 0
        app = web.Application()
        app.router.add_post("/detect", self._detect)
        app.router.add_get("/healthz", self._healthz)
        self.server = TestServer(app)

    async def _detect(self, request: web.Request) -> web.Response:
        self.detect_calls += 1
        if self.delay_s:
            await asyncio.sleep(self.delay_s)
        return web.json_response({"served_by": self.name}, status=self.status)

    async def _healthz(self, request: web.Request) -> web.Response:
        return web.json_response({}, status=self.health_status)

    async def start(self) -> str:
        await self.server.start_server()
        return f"http://{self.server.host}:{self.server.port}"

    async def stop(self) -> None:
        await self.server.close()


async def _with_replicas(n):
    replicas = [ScriptedReplica(f"r{i}") for i in range(n)]
    urls = [await r.start() for r in replicas]
    return replicas, urls


def test_round_robin_spreads_load():
    async def run():
        replicas, urls = await _with_replicas(2)
        pool = ReplicaPool(urls, health_interval_s=0.05)
        for _ in range(8):
            body = await pool.detect(PAYLOAD)
            assert body["served_by"] in ("r0", "r1")
        assert replicas[0].detect_calls > 0 and replicas[1].detect_calls > 0
        assert pool.requests_total == 8 and pool.failures_total == 0
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_replay_on_dead_replica_and_ejection():
    """A dead endpoint (connection refused — the preemption signature) must
    be invisible to the client: every request replays to the survivor, and
    after eject_threshold consecutive failures the dead replica is ejected
    so later requests stop paying the connect attempt."""

    async def run():
        replicas, urls = await _with_replicas(1)
        dead = "http://127.0.0.1:1"  # reserved port: connect refused
        pool = ReplicaPool(
            [dead, urls[0]],
            eject_threshold=2,
            backoff_base_s=5.0,  # long: must not un-eject mid-test
            health_interval_s=30.0,
        )
        for _ in range(6):
            body = await pool.detect(PAYLOAD)
            assert body["served_by"] == "r0"
        assert pool.replays_total >= 1
        assert pool.ejections_total >= 1
        snap = pool.snapshot()
        dead_snap = next(r for r in snap["replicas"] if r["url"] == dead)
        assert not dead_snap["available"]
        assert dead_snap["ejected_for_s"] > 0
        # once ejected, new requests go straight to the survivor
        calls_before = replicas[0].detect_calls
        await pool.detect(PAYLOAD)
        assert replicas[0].detect_calls == calls_before + 1
        assert pool.failures_total == 0  # nothing client-visible
        await pool.stop()
        await replicas[0].stop()

    asyncio.run(run())


def test_replay_on_503_draining_replica():
    async def run():
        replicas, urls = await _with_replicas(2)
        replicas[0].status = 503  # draining / breaker open
        pool = ReplicaPool(urls, health_interval_s=30.0)
        for _ in range(4):
            body = await pool.detect(PAYLOAD)
            assert body["served_by"] == "r1"
        assert pool.replays_total >= 1
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_health_loop_unejects_recovered_replica():
    async def run():
        replicas, urls = await _with_replicas(2)
        replicas[0].status = 500
        replicas[0].health_status = 503
        pool = ReplicaPool(
            urls,
            eject_threshold=1,
            backoff_base_s=0.05,
            backoff_max_s=0.1,
            health_interval_s=0.05,
        )
        await pool.start()
        await pool.detect(PAYLOAD)  # trips the ejection on r0 (or serves r1)
        await pool.detect(PAYLOAD)
        assert pool.ejections_total >= 1
        # recover r0: health loop should reset it within a few intervals
        replicas[0].status = 200
        replicas[0].health_status = 200
        for _ in range(100):
            r0 = pool.replicas[0]
            if r0.healthy and r0.consecutive_failures == 0 and r0.ejected_until == 0.0:
                break
            await asyncio.sleep(0.02)
        assert pool.replicas[0].healthy
        assert pool.replicas[0].consecutive_failures == 0
        served = set()
        for _ in range(8):
            served.add((await pool.detect(PAYLOAD))["served_by"])
        assert "r0" in served  # actually taking traffic again
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_hedging_wins_on_slow_replica():
    async def run():
        replicas, urls = await _with_replicas(2)
        replicas[0].delay_s = 1.0  # alive but drowning
        pool = ReplicaPool(urls, hedge_after_s=0.05, health_interval_s=30.0)
        t0 = asyncio.get_running_loop().time()
        # r0 and r1 alternate as primary; when slow r0 is primary the hedge
        # fires and r1's answer wins
        bodies = [await pool.detect(PAYLOAD) for _ in range(2)]
        elapsed = asyncio.get_running_loop().time() - t0
        assert all(b["served_by"] == "r1" for b in bodies)
        assert elapsed < 1.0  # never waited out the slow replica
        assert pool.hedges_total >= 1
        assert pool.hedge_wins_total >= 1
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_pool_exhausted_is_loud():
    async def run():
        pool = ReplicaPool(
            ["http://127.0.0.1:1", "http://127.0.0.1:2"],
            health_interval_s=30.0,
        )
        with pytest.raises(PoolExhaustedError):
            await pool.detect(PAYLOAD)
        assert pool.failures_total == 1
        snap = pool.snapshot()
        assert snap["pool_failures_total"] == 1
        assert snap["pool_requests_total"] == 1
        await pool.stop()

    asyncio.run(run())


def test_snapshot_counter_fields():
    async def run():
        replicas, urls = await _with_replicas(1)
        pool = ReplicaPool(urls, health_interval_s=30.0)
        await pool.detect(PAYLOAD)
        snap = pool.snapshot()
        for key in (
            "pool_requests_total",
            "pool_replays_total",
            "pool_hedges_total",
            "pool_hedge_wins_total",
            "pool_ejections_total",
            "pool_failures_total",
            "replicas",
        ):
            assert key in snap
        (r,) = snap["replicas"]
        assert r["requests"] == 1 and r["healthy"] and r["available"]
        await pool.stop()
        await replicas[0].stop()

    asyncio.run(run())


def test_router_app_routes():
    """The edge router surface: /detect forwarded, /healthz reflects pool
    availability, /metrics serves the pool snapshot."""
    from aiohttp.test_utils import TestClient

    from spotter_tpu.serving.router import make_router_app

    async def run():
        replicas, urls = await _with_replicas(2)
        pool = ReplicaPool(urls, health_interval_s=0.1)
        app = make_router_app(pool)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post("/detect", json=PAYLOAD)
            assert resp.status == 200
            assert (await resp.json())["served_by"] in ("r0", "r1")

            health = await client.get("/healthz")
            assert health.status == 200
            body = await health.json()
            assert body["available_replicas"] == 2

            live = await client.get("/livez")
            assert live.status == 200

            metrics = await (await client.get("/metrics")).json()
            assert metrics["pool_requests_total"] == 1
            assert len(metrics["replicas"]) == 2

            bad = await client.post("/detect", data=b"{nope")
            assert bad.status == 400
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_router_503_when_pool_exhausted():
    from aiohttp.test_utils import TestClient

    from spotter_tpu.serving.router import make_router_app

    async def run():
        pool = ReplicaPool(["http://127.0.0.1:1"], health_interval_s=30.0)
        app = make_router_app(pool)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post("/detect", json=PAYLOAD)
            assert resp.status == 503
            assert "Retry-After" in resp.headers

    asyncio.run(run())


def test_pool_requires_endpoints():
    with pytest.raises(ValueError):
        ReplicaPool([])


# ---- ISSUE 6: suspended-pool fast 503, retry budget, dynamic membership ----


def test_all_ejected_fails_fast_with_retry_after():
    """Regression: a pool whose every replica is ejected used to wait out
    connect attempts and round pauses against an empty candidate set; it
    must raise PoolSuspendedError immediately with a Retry-After hint."""

    async def run():
        pool = ReplicaPool(
            ["http://127.0.0.1:1", "http://127.0.0.1:2"],
            health_interval_s=30.0,
        )
        now = time.monotonic()
        for r in pool.replicas:
            r.ejected_until = now + 30.0
        t0 = time.perf_counter()
        with pytest.raises(PoolSuspendedError) as ei:
            await pool.request("/detect", PAYLOAD)
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.2  # no connects, no round pauses
        assert ei.value.retry_after_s > 0
        snap = pool.snapshot()
        assert snap["pool_suspended_total"] == 1
        assert snap["pool_failures_total"] == 1
        await pool.stop()

    asyncio.run(run())


def test_router_503_immediate_when_all_ejected():
    from aiohttp.test_utils import TestClient

    from spotter_tpu.serving.router import make_router_app

    async def run():
        pool = ReplicaPool(["http://127.0.0.1:1"], health_interval_s=30.0)
        pool.replicas[0].ejected_until = time.monotonic() + 30.0
        app = make_router_app(pool)
        async with TestClient(TestServer(app)) as client:
            t0 = time.perf_counter()
            resp = await client.post("/detect", json=PAYLOAD)
            elapsed = time.perf_counter() - t0
            assert resp.status == 503
            assert int(resp.headers["Retry-After"]) >= 1
            assert elapsed < 0.5

    asyncio.run(run())


def test_empty_pool_and_dynamic_membership():
    async def run():
        pool = ReplicaPool([], allow_empty=True, health_interval_s=30.0)
        with pytest.raises(PoolSuspendedError):
            await pool.request("/detect", PAYLOAD)
        replicas, urls = await _with_replicas(1)
        pool.add_endpoint(urls[0], healthy=True)
        assert (await pool.detect(PAYLOAD))["served_by"] == "r0"
        # adding the same URL twice is idempotent
        pool.add_endpoint(urls[0])
        assert len(pool.replicas) == 1
        pool.remove_endpoint(urls[0])
        with pytest.raises(PoolSuspendedError):
            await pool.request("/detect", PAYLOAD)
        await pool.stop()
        await replicas[0].stop()

    asyncio.run(run())


def test_retry_budget_exhaustion_fails_fast():
    """With a zero budget the FIRST attempt is still free, but the replay a
    failing replica would trigger is refused — the request fails fast
    instead of amplifying a correlated failure."""

    async def run():
        replicas, urls = await _with_replicas(2)
        for r in replicas:
            r.status = 500
        pool = ReplicaPool(
            urls,
            health_interval_s=30.0,
            retry_budget=RetryBudget(pct=0.0, min_retries=0),
        )
        with pytest.raises(RetryBudgetExhaustedError) as ei:
            await pool.detect(PAYLOAD)
        assert ei.value.retry_after_s > 0
        assert pool.replays_total == 0  # the replay never launched
        snap = pool.snapshot()
        assert snap["pool_retry_budget_exhausted_total"] == 1
        assert snap["pool_failures_total"] == 1
        # only the first (free) attempt reached a replica
        assert sum(r.detect_calls for r in replicas) == 1
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_retry_budget_scales_with_request_rate():
    t = {"now": 0.0}
    rb = RetryBudget(pct=10.0, min_retries=0, window_s=10.0,
                     clock=lambda: t["now"])
    for _ in range(100):
        rb.record_request()
    assert rb.allowed() == 10.0
    assert sum(rb.try_spend() for _ in range(15)) == 10
    assert rb.exhausted_total == 5
    # the window rolls: old requests (and spent retries) expire together
    t["now"] = 11.0
    assert rb.allowed() == 0.0
    assert not rb.try_spend()
    # fresh traffic reopens the budget
    for _ in range(50):
        rb.record_request()
    assert rb.try_spend()
    snap = rb.snapshot()
    assert snap["window_requests"] == 50 and snap["window_retries"] == 1


def test_default_budget_floor_preserves_single_death_failover():
    """The floor exists so plain one-replica failover (ISSUE 2 semantics)
    still replays freely: a dead replica plus a healthy one must keep
    serving every request with the DEFAULT budget."""

    async def run():
        replicas, urls = await _with_replicas(1)
        pool = ReplicaPool(
            ["http://127.0.0.1:1", urls[0]],
            eject_threshold=2,
            backoff_base_s=5.0,
            health_interval_s=30.0,
        )
        for _ in range(8):
            assert (await pool.detect(PAYLOAD))["served_by"] == "r0"
        assert pool.retry_budget.exhausted_total == 0
        await pool.stop()
        await replicas[0].stop()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# mixed-version request pinning (ISSUE 15)


def test_replay_pins_to_first_attempt_version():
    """During a mixed-version window a failed attempt replays onto a
    replica of the SAME deploy version: a request must never be
    re-processed by an incompatible build while a same-version candidate
    exists."""

    async def run():
        replicas, urls = await _with_replicas(3)
        replicas[0].status = 500  # the v1 replica that fails
        pool = ReplicaPool(urls, health_interval_s=30.0)
        pool.set_version(urls[0], "v1")
        pool.set_version(urls[1], "v1")
        pool.set_version(urls[2], "v2")
        # force the first attempt onto the failing v1 replica
        body = (
            await pool.request("/detect", PAYLOAD, prefer=[urls[0]])
        ).json()
        assert body["served_by"] == "r1"  # the same-version survivor
        assert replicas[2].detect_calls == 0  # v2 never touched
        snap = pool.snapshot()
        assert snap["pool_version_pinned_replays_total"] == 1
        assert snap["pool_version_pin_relaxed_total"] == 0
        versions = {r["url"]: r["version"] for r in snap["replicas"]}
        assert versions == {urls[0]: "v1", urls[1]: "v1", urls[2]: "v2"}
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_replay_relaxes_pin_when_no_same_version_left():
    """With no same-version candidate left, availability beats skew
    purity: the replay relaxes the pin (counted) instead of failing the
    client."""

    async def run():
        replicas, urls = await _with_replicas(2)
        replicas[0].status = 500
        pool = ReplicaPool(urls, health_interval_s=30.0)
        pool.set_version(urls[0], "v1")
        pool.set_version(urls[1], "v2")
        body = (
            await pool.request("/detect", PAYLOAD, prefer=[urls[0]])
        ).json()
        assert body["served_by"] == "r1"
        snap = pool.snapshot()
        assert snap["pool_version_pin_relaxed_total"] == 1
        assert pool.failures_total == 0  # nothing client-visible
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_hedge_is_version_strict():
    """A hedge double-processes by design — exactly what must never
    straddle two versions: with only a cross-version backup available the
    hedge is skipped (un-hedged waiting, no error); a same-version backup
    restores hedging."""

    async def run():
        replicas, urls = await _with_replicas(2)
        replicas[0].delay_s = 0.25  # slow primary: the hedge trigger fires
        pool = ReplicaPool(urls, health_interval_s=30.0, hedge_after_s=0.05)
        pool.set_version(urls[0], "v1")
        pool.set_version(urls[1], "v2")
        body = (
            await pool.request("/detect", PAYLOAD, prefer=[urls[0]])
        ).json()
        assert body["served_by"] == "r0"  # waited the slow primary out
        assert pool.hedges_total == 0  # no same-version backup: no hedge
        assert replicas[1].detect_calls == 0
        # same build on both: the hedge fires and the fast backup wins
        pool.set_version(urls[1], "v1")
        body = (
            await pool.request("/detect", PAYLOAD, prefer=[urls[0]])
        ).json()
        assert body["served_by"] == "r1"
        assert pool.hedges_total == 1
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_pinned_weight_holds_canary_share():
    """The rollout canary hold: a pinned weight caps a replica's share of
    blind round-robin traffic via the smooth-weighted-RR path."""

    async def run():
        replicas, urls = await _with_replicas(3)
        pool = ReplicaPool(urls, health_interval_s=30.0)
        pool.set_weight(urls[2], 0.1)
        for _ in range(60):
            await pool.detect(PAYLOAD)
        share = replicas[2].detect_calls / 60.0
        # 0.1 / (1 + 1 + 0.1) ~ 4.8%; generous bound still proves the hold
        assert share < 0.15, f"canary share {share:.2f}"
        # clearing the pin restores plain round-robin
        pool.set_weight(urls[2], None)
        before = replicas[2].detect_calls
        for _ in range(30):
            await pool.detect(PAYLOAD)
        assert replicas[2].detect_calls - before >= 8
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_probe_in_flight_cannot_resurrect_retired_member():
    """The retire/adopt race (ISSUE 16 satellite): a health probe that was
    awaiting /healthz when the member was retired must NOT mutate the stale
    Replica on completion — success would mark a retiring member healthy
    mid-drain, and failure on a removed-and-readded URL would smear state
    onto an object no longer in the ring."""

    async def run():
        replicas, urls = await _with_replicas(2)
        pool = ReplicaPool(urls, health_interval_s=30.0)
        url = urls[0]
        r = pool.replica_for(url)
        r.healthy = False
        r.consecutive_failures = 3

        gate = asyncio.Event()
        real_get = pool.client.get

        async def gated_get(u, **kw):
            await gate.wait()
            return await real_get(u, **kw)

        pool.client.get = gated_get
        probe = asyncio.create_task(pool._probe(r))
        await asyncio.sleep(0.02)  # probe parked on the gate
        # the retire path runs while the probe is in flight
        assert pool.remove_endpoint(url) is r
        gate.set()
        await probe  # /healthz answers 200 for a replica no longer pooled
        assert pool.replica_for(url) is None
        assert r.healthy is False  # the stale object was not "promoted"
        assert r.consecutive_failures == 3

        # removed-and-readded: the NEW Replica ("starting") must only be
        # promoted by ITS OWN probe, never by the stale one completing
        gate.clear()
        probe = asyncio.create_task(pool._probe(r))
        await asyncio.sleep(0.02)
        r2 = pool.add_endpoint(url, healthy=False)
        assert r2 is not r
        gate.set()
        await probe
        assert r2.healthy is False
        await pool._probe(r2)  # its own probe promotes it
        assert r2.healthy is True

        pool.client.get = real_get
        await pool.stop()
        for rep in replicas:
            await rep.stop()

    asyncio.run(run())
