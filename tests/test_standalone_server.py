"""HTTP surface test for the standalone server: /detect wire contract,
/healthz, /metrics — driven through aiohttp's test client."""

import asyncio
import os
from io import BytesIO
from unittest.mock import AsyncMock

import httpx
import pytest
import numpy as np
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

os.environ["SPOTTER_TPU_TINY"] = "1"

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.engine import InferenceEngine
from spotter_tpu.models import build_detector
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.standalone import make_app


def _client_returning_image():
    img = Image.fromarray(np.full((32, 32, 3), 128, np.uint8))
    buf = BytesIO()
    img.save(buf, format="JPEG")
    resp = AsyncMock()
    resp.content = buf.getvalue()
    resp.raise_for_status = lambda: None
    client = AsyncMock(spec=httpx.AsyncClient)
    client.get.return_value = resp
    return client


def test_detect_healthz_metrics_round_trip():
    async def run():
        built = build_detector("PekingU/rtdetr_v2_r18vd")
        engine = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2))
        detector = AmenitiesDetector(
            engine, MicroBatcher(engine, max_delay_ms=1.0), _client_returning_image()
        )
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            health = await client.get("/healthz")
            assert health.status == 200
            body = await health.json()
            assert body["status"] == "ok"
            assert body["breaker"] == "closed" and body["draining"] is False

            live = await client.get("/livez")
            assert live.status == 200
            assert (await live.json()) == {"status": "alive"}

            resp = await client.post(
                "/detect", json={"image_urls": ["http://example.com/room.jpg"]}
            )
            assert resp.status == 200
            body = await resp.json()
            assert set(body.keys()) == {"amenities_description", "images"}
            (img_result,) = body["images"]
            assert set(img_result.keys()) == {"url", "detections", "labeled_image_base64"}

            bad = await client.post("/detect", data=b"{not json")
            assert bad.status == 400

            metrics = await client.get("/metrics")
            snap = await metrics.json()
            assert snap["images_total"] >= 1
            assert snap["latency_ms_p50"] > 0

    asyncio.run(run())


def test_sharded_serving_via_mesh_env(monkeypatch):
    """SPOTTER_TPU_MESH makes the production bootstrap serve off a real mesh
    (VERDICT r1 weak #5): the full /detect wire contract must hold with the
    batch sharded over the virtual 8-device "dp" axis."""

    async def run():
        monkeypatch.setenv("SPOTTER_TPU_MESH", "dp=4,tp=2")
        from spotter_tpu.serving.app import build_detector_app

        detector = build_detector_app(
            model_name="PekingU/rtdetr_v2_r18vd",
            threshold=0.0,
            batch_buckets=(1, 4),
            max_delay_ms=1.0,
        )
        assert detector.engine.mesh is not None
        assert detector.engine.mesh.shape == {"dp": 4, "tp": 2}
        # buckets rounded up to dp multiples, never shrunk
        assert detector.engine.batch_buckets == (4,)
        detector.client = _client_returning_image()
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post(
                "/detect",
                json={"image_urls": [f"http://example.com/{i}.jpg" for i in range(3)]},
            )
            assert resp.status == 200
            body = await resp.json()
            assert len(body["images"]) == 3
            for img_result in body["images"]:
                assert "labeled_image_base64" in img_result

    asyncio.run(run())


def test_serve_dp_env_aggregate_ladder(monkeypatch):
    """SPOTTER_TPU_SERVE_DP=2 is the first-class dp-sharded serving config
    (ISSUE 3): the ladder keeps per-chip semantics and is scaled to the
    aggregate (batcher fills dp × per-chip bucket), the engine gets a dp=2
    tp=1 mesh, and the /detect wire contract holds end-to-end."""

    async def run():
        monkeypatch.setenv("SPOTTER_TPU_SERVE_DP", "2")
        from spotter_tpu.serving.app import build_detector_app

        detector = build_detector_app(
            model_name="PekingU/rtdetr_v2_r18vd",
            threshold=0.0,
            batch_buckets=(1, 2),
            max_delay_ms=1.0,
        )
        assert detector.engine.mesh is not None
        assert detector.engine.mesh.shape == {"dp": 2, "tp": 1}
        assert detector.engine.dp == 2
        assert detector.engine.batch_buckets == (2, 4)  # aggregate, not rounded
        assert detector.batcher.max_batch == 4
        health = detector.health()
        assert health["dp"] == 2 and health["device_preprocess"] is False
        detector.client = _client_returning_image()
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post(
                "/detect",
                json={"image_urls": [f"http://example.com/{i}.jpg" for i in range(3)]},
            )
            assert resp.status == 200
            body = await resp.json()
            assert len(body["images"]) == 3
            metrics = await client.get("/metrics")
            snap = await metrics.json()
            assert snap["aggregate_bucket"] == 4

    asyncio.run(run())


def test_explicit_mesh_wins_over_serve_dp(monkeypatch):
    """Both knobs set: the expert SPOTTER_TPU_MESH spec is authoritative
    (keeps its round-up semantics); SERVE_DP must not double-scale."""
    monkeypatch.setenv("SPOTTER_TPU_SERVE_DP", "4")
    monkeypatch.setenv("SPOTTER_TPU_MESH", "dp=2")
    from spotter_tpu.serving.app import build_detector_app

    detector = build_detector_app(
        model_name="PekingU/rtdetr_v2_r18vd", threshold=0.0, batch_buckets=(1, 2)
    )
    assert detector.engine.mesh.shape == {"dp": 2, "tp": 1}
    assert detector.engine.batch_buckets == (2,)  # round-up, not ×dp


def test_serve_dp_env_parsing(monkeypatch):
    from spotter_tpu.serving.app import serve_dp_from_env

    monkeypatch.delenv("SPOTTER_TPU_SERVE_DP", raising=False)
    assert serve_dp_from_env() == 1
    monkeypatch.setenv("SPOTTER_TPU_SERVE_DP", "4")
    assert serve_dp_from_env() == 4
    monkeypatch.setenv("SPOTTER_TPU_SERVE_DP", "all")
    assert serve_dp_from_env() >= 1
    monkeypatch.setenv("SPOTTER_TPU_SERVE_DP", "two")
    with pytest.raises(ValueError):
        serve_dp_from_env()


def test_metrics_expose_ingest_pipeline(monkeypatch):
    """/metrics carries the new ingest observability (ISSUE 3):
    h2d_bytes_total/bytes-per-image, decode_pool_queue_depth, and per-stage
    staging/device histograms (p50/p90/p99), for both ingest modes."""

    async def run():
        monkeypatch.setenv("SPOTTER_TPU_DEVICE_PREPROCESS", "1")
        built = build_detector("PekingU/rtdetr_v2_r18vd")
        engine = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2))
        assert engine.device_preprocess  # env knob armed the uint8 path
        detector = AmenitiesDetector(
            engine, MicroBatcher(engine, max_delay_ms=1.0), _client_returning_image()
        )
        assert detector.health()["device_preprocess"] is True
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post(
                "/detect", json={"image_urls": ["http://example.com/room.jpg"]}
            )
            assert resp.status == 200
            snap = await (await client.get("/metrics")).json()
            assert snap["h2d_bytes_total"] > 0
            assert snap["h2d_bytes_per_image"] > 0
            assert "decode_pool_queue_depth" in snap
            # the unified obs.STAGES vocabulary (ISSUE 7 satellite): the
            # old "preprocess" alias is gone — decode + h2d ARE staging
            from spotter_tpu import obs

            for stage in obs.ENGINE_STAGES:
                for tag in ("p50", "p90", "p99"):
                    assert f"stage_{stage}_ms_{tag}" in snap
            assert "stage_preprocess_ms_p50" not in snap

    asyncio.run(run())


def test_batch_buckets_env_knob(monkeypatch):
    """SPOTTER_TPU_BATCH_BUCKETS applies the per-model ladder guidance
    (e.g. R18's measured batch-16 peak) without code changes; malformed
    specs fail loudly at startup, not as silent defaults."""
    from spotter_tpu.serving.app import build_detector_app, parse_batch_buckets

    assert parse_batch_buckets("1,2,4,8,16") == (1, 2, 4, 8, 16)
    for bad in ("", "0,2", "8,4", "4,4", "a,b"):
        with pytest.raises(ValueError):
            parse_batch_buckets(bad)

    monkeypatch.setenv("SPOTTER_TPU_BATCH_BUCKETS", "2,16")
    detector = build_detector_app(
        model_name="PekingU/rtdetr_v2_r18vd", threshold=0.0, max_delay_ms=1.0
    )
    assert detector.engine.batch_buckets == (2, 16)


def test_batch_buckets_empty_env_fails_loudly(monkeypatch):
    from spotter_tpu.serving.app import build_detector_app

    monkeypatch.setenv("SPOTTER_TPU_BATCH_BUCKETS", "")
    with pytest.raises(ValueError):
        build_detector_app(model_name="PekingU/rtdetr_v2_r18vd")
