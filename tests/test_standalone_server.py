"""HTTP surface test for the standalone server: /detect wire contract,
/healthz, /metrics — driven through aiohttp's test client."""

import asyncio
import os
from io import BytesIO
from unittest.mock import AsyncMock

import httpx
import numpy as np
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

os.environ["SPOTTER_TPU_TINY"] = "1"

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.engine import InferenceEngine
from spotter_tpu.models import build_detector
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.standalone import make_app


def _client_returning_image():
    img = Image.fromarray(np.full((32, 32, 3), 128, np.uint8))
    buf = BytesIO()
    img.save(buf, format="JPEG")
    resp = AsyncMock()
    resp.content = buf.getvalue()
    resp.raise_for_status = lambda: None
    client = AsyncMock(spec=httpx.AsyncClient)
    client.get.return_value = resp
    return client


def test_detect_healthz_metrics_round_trip():
    async def run():
        built = build_detector("PekingU/rtdetr_v2_r18vd")
        engine = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2))
        detector = AmenitiesDetector(
            engine, MicroBatcher(engine, max_delay_ms=1.0), _client_returning_image()
        )
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            health = await client.get("/healthz")
            assert health.status == 200
            assert (await health.json()) == {"status": "ok"}

            resp = await client.post(
                "/detect", json={"image_urls": ["http://example.com/room.jpg"]}
            )
            assert resp.status == 200
            body = await resp.json()
            assert set(body.keys()) == {"amenities_description", "images"}
            (img_result,) = body["images"]
            assert set(img_result.keys()) == {"url", "detections", "labeled_image_base64"}

            bad = await client.post("/detect", data=b"{not json")
            assert bad.status == 400

            metrics = await client.get("/metrics")
            snap = await metrics.json()
            assert snap["images_total"] >= 1
            assert snap["latency_ms_p50"] > 0

    asyncio.run(run())
