"""RepVGG re-parameterization (models/rtdetr.py REP_FUSE): the fused
single-conv path must be checkpoint-compatible with (identical param tree)
and numerically equivalent to (up to float reassociation) the unfused
conv3x3+BN + conv1x1+BN sum it replaces.

The torch reference never applies this inference identity (HF RTDetr runs
RepVggBlock unfused — modeling_rt_detr_v2); it is a TPU-side serving
optimization, so its correctness proof lives here rather than in the torch
parity tier.
"""

import jax
import jax.numpy as jnp
import numpy as np

from spotter_tpu.models import rtdetr
from spotter_tpu.utils.precision import DTYPE_ENV


def _perturbed_params(module, rng, x):
    params = module.init(jax.random.PRNGKey(0), x)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    out = []
    for leaf in leaves:
        vals = rng.standard_normal(leaf.shape).astype(np.float32) * 0.5
        if leaf.ndim == 1:  # bn stats: keep var positive, scale/bias generic
            vals = np.abs(vals) + 0.5
        out.append(jnp.asarray(vals, leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


def test_rep_fuse_param_tree_and_values_match(monkeypatch):
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 16, 16, 32)), jnp.float32
    )
    blk = rtdetr.CSPRepLayer(out_channels=48, hidden_channels=32)

    monkeypatch.setattr(rtdetr, "REP_FUSE", False)
    p_unfused = blk.init(jax.random.PRNGKey(0), x)
    monkeypatch.setattr(rtdetr, "REP_FUSE", True)
    p_fused = blk.init(jax.random.PRNGKey(0), x)

    assert jax.tree_util.tree_structure(p_unfused) == jax.tree_util.tree_structure(
        p_fused
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(p_unfused), jax.tree_util.tree_leaves(p_fused)
    ):
        assert a.shape == b.shape and a.dtype == b.dtype

    params = _perturbed_params(blk, np.random.default_rng(1), x)
    monkeypatch.setattr(rtdetr, "REP_FUSE", False)
    y_unfused = blk.apply(params, x)
    monkeypatch.setattr(rtdetr, "REP_FUSE", True)
    y_fused = blk.apply(params, x)

    scale = float(jnp.max(jnp.abs(y_unfused)))
    np.testing.assert_allclose(
        np.asarray(y_fused), np.asarray(y_unfused), atol=1e-5 * max(scale, 1.0)
    )


def test_rep_fuse_default_follows_policy(monkeypatch):
    monkeypatch.delenv("SPOTTER_TPU_REP_FUSE", raising=False)
    monkeypatch.setenv(DTYPE_ENV, "bfloat16")
    assert rtdetr._rep_fuse_default() is True
    monkeypatch.setenv(DTYPE_ENV, "float32")
    assert rtdetr._rep_fuse_default() is False
    # "mixed" pins the transformer half (where RepVgg lives) to exact fp32
    monkeypatch.setenv(DTYPE_ENV, "mixed")
    assert rtdetr._rep_fuse_default() is False
    monkeypatch.setenv("SPOTTER_TPU_REP_FUSE", "1")
    assert rtdetr._rep_fuse_default() is True
    monkeypatch.setenv("SPOTTER_TPU_REP_FUSE", "0")
    monkeypatch.setenv(DTYPE_ENV, "bfloat16")
    assert rtdetr._rep_fuse_default() is False
