"""Chaos suite (ISSUE 1 acceptance): under injected fetch failures, engine
exceptions, and a simulated engine hang, no request awaits forever — every
caller gets a result, a structured error, or a shed response within its
deadline, and the pump keeps serving subsequent traffic. Faults come from
spotter_tpu/testing/faults.py, the same harness a chaos-staging server arms
via SPOTTER_TPU_FAULTS."""

import asyncio
import time
from io import BytesIO
from unittest.mock import AsyncMock

import httpx
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

from spotter_tpu.engine.batcher import BatchTimeoutError, MicroBatcher
from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.schemas import DetectionErrorResult, DetectionSuccessResult
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.resilience import (
    CircuitBreaker,
    Deadline,
    DeadlineExceededError,
)
from spotter_tpu.serving.standalone import make_app
from spotter_tpu.testing import faults

DETS = [{"label": "tv", "score": 0.9, "box": [1.0, 2.0, 20.0, 30.0]}]


class FakeEngine:
    def __init__(self, detections=DETS):
        self.detections = detections
        self.metrics = Metrics()
        self.batch_buckets = (1, 2, 4)
        self.calls = []
        self.broken = False

    def detect(self, images):
        if self.broken:
            raise RuntimeError("engine down")
        self.calls.append(len(images))
        return [list(self.detections) for _ in images]


@pytest.fixture(autouse=True)
def _zero_retry_backoff(monkeypatch):
    import spotter_tpu.serving.detector as det_mod

    monkeypatch.setattr(det_mod, "FETCH_RETRY_WAIT_MIN_S", 0.0)
    monkeypatch.setattr(det_mod, "FETCH_RETRY_WAIT_MAX_S", 0.0)


def _image_bytes(w=32, h=32):
    img = Image.fromarray(np.full((h, w, 3), 128, np.uint8))
    buf = BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


def _client_returning_image():
    resp = AsyncMock()
    resp.content = _image_bytes()
    resp.raise_for_status = lambda: None
    client = AsyncMock(spec=httpx.AsyncClient)
    client.get.return_value = resp
    return client


def _img():
    return Image.fromarray(np.zeros((8, 8, 3), np.uint8))


def _detector(engine=None, **batcher_kwargs):
    engine = engine or FakeEngine()
    batcher_kwargs.setdefault("max_delay_ms", 1.0)
    batcher_kwargs.setdefault(
        "breaker", CircuitBreaker(threshold=100, metrics=engine.metrics)
    )
    batcher = MicroBatcher(engine, **batcher_kwargs)
    return AmenitiesDetector(engine, batcher, _client_returning_image()), engine


def test_fetch_faults_contained_and_pump_survives():
    detector, engine = _detector()

    async def run():
        with faults.inject(fetch_error=-1):
            broken = await detector.detect(
                {"image_urls": ["http://e.com/a.jpg", "http://e.com/b.jpg"]}
            )
        healthy = await detector.detect({"image_urls": ["http://e.com/c.jpg"]})
        await detector.batcher.stop()
        return broken, healthy

    broken, healthy = asyncio.run(run())
    assert all(isinstance(r, DetectionErrorResult) for r in broken.images)
    assert all(r.error.startswith("HTTP Error:") for r in broken.images)
    (ok,) = healthy.images
    assert isinstance(ok, DetectionSuccessResult)


def test_malformed_image_contained():
    detector, _ = _detector()

    async def run():
        with faults.inject(malformed_image=1):
            broken = await detector.detect({"image_urls": ["http://e.com/a.jpg"]})
        healthy = await detector.detect({"image_urls": ["http://e.com/b.jpg"]})
        await detector.batcher.stop()
        return broken, healthy

    broken, healthy = asyncio.run(run())
    (bad,) = broken.images
    assert isinstance(bad, DetectionErrorResult)
    assert bad.error.startswith("Processing Error:")
    assert isinstance(healthy.images[0], DetectionSuccessResult)


def test_engine_exception_fails_only_its_batch():
    detector, engine = _detector()

    async def run():
        with faults.inject(engine_error=1):
            broken = await detector.detect({"image_urls": ["http://e.com/a.jpg"]})
        healthy = await detector.detect({"image_urls": ["http://e.com/b.jpg"]})
        await detector.batcher.stop()
        return broken, healthy

    broken, healthy = asyncio.run(run())
    (bad,) = broken.images
    assert isinstance(bad, DetectionErrorResult)
    assert "injected engine failure" in bad.error
    assert isinstance(healthy.images[0], DetectionSuccessResult)
    assert engine.metrics.snapshot()["errors_total"] >= 1


def test_engine_hang_watchdog_frees_slot_and_pump_survives():
    """A wedged engine call must fail its futures via the watchdog and
    release its in-flight slot — not deadlock the pump forever."""
    engine = FakeEngine()
    batcher = MicroBatcher(
        engine,
        max_batch=1,
        max_delay_ms=1.0,
        max_in_flight=1,
        batch_timeout_ms=200.0,
        breaker=CircuitBreaker(threshold=100, metrics=engine.metrics),
    )

    async def run():
        t0 = time.monotonic()
        with faults.inject(engine_hang_s=30.0) as plan:
            with pytest.raises(BatchTimeoutError):
                await batcher.submit(_img(), deadline=Deadline.after(5.0))
            hung_for = time.monotonic() - t0
            plan.release.set()  # un-wedge the orphaned worker thread
        result = await batcher.submit(_img())
        await batcher.stop()
        return hung_for, result

    hung_for, result = asyncio.run(run())
    assert hung_for < 2.0  # watchdog (200 ms), not the 30 s hang or 5 s deadline
    assert result == DETS
    snap = engine.metrics.snapshot()
    assert snap["batch_timeouts_total"] == 1


def test_deadline_bounds_slow_fetch():
    detector, _ = _detector()

    async def run():
        t0 = time.monotonic()
        with faults.inject(fetch_delay_s=5.0):
            resp = await detector.detect(
                {"image_urls": ["http://e.com/a.jpg"]},
                deadline=Deadline.after(0.15),
            )
        elapsed = time.monotonic() - t0
        await detector.batcher.stop()
        return resp, elapsed

    resp, elapsed = asyncio.run(run())
    (r,) = resp.images
    assert isinstance(r, DetectionErrorResult)
    assert r.error.startswith("Deadline exceeded:")
    assert elapsed < 1.0  # bounded by the deadline, not the injected delay


def test_deadline_bounds_hung_device_call():
    engine = FakeEngine()
    batcher = MicroBatcher(
        engine,
        max_batch=1,
        max_delay_ms=1.0,
        batch_timeout_ms=0.0,  # watchdog off: the deadline alone must bound it
        breaker=CircuitBreaker(threshold=100, metrics=engine.metrics),
    )

    async def run():
        with faults.inject(engine_hang_s=10.0) as plan:
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceededError):
                await batcher.submit(_img(), deadline=Deadline.after(0.15))
            elapsed = time.monotonic() - t0
            plan.release.set()
        await batcher.stop()
        return elapsed

    elapsed = asyncio.run(run())
    assert elapsed < 1.0
    assert engine.metrics.snapshot()["deadline_exceeded_total"] == 1


def test_server_breaker_healthz_cycle():
    """Acceptance: /healthz 503 while the breaker is open, 200 again after a
    successful half-open probe, transitions visible in /metrics."""
    engine = FakeEngine()
    engine.broken = True
    # cooldown long enough that the shed-while-open assertions can't race it;
    # the test elapses it deterministically by rewinding _opened_at
    breaker = CircuitBreaker(threshold=2, cooldown_s=60.0, metrics=engine.metrics)
    batcher = MicroBatcher(engine, max_delay_ms=1.0, breaker=breaker)
    detector = AmenitiesDetector(engine, batcher, _client_returning_image())

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            payload = {"image_urls": ["http://e.com/a.jpg"]}
            # two engine-failure batches: contained per-image (HTTP 200) but
            # counted by the breaker, which trips at threshold 2
            for _ in range(2):
                resp = await client.post("/detect", json=payload)
                assert resp.status == 200
                body = await resp.json()
                assert "Processing Error" in body["images"][0]["error"]
            assert breaker.state == CircuitBreaker.OPEN

            health = await client.get("/healthz")
            assert health.status == 503
            assert (await health.json())["breaker"] == "open"
            live = await client.get("/livez")
            assert live.status == 200  # liveness is separate from readiness

            shed = await client.post("/detect", json=payload)
            assert shed.status == 503
            assert "Retry-After" in shed.headers

            metrics = await (await client.get("/metrics")).json()
            assert metrics["breaker_state"] == "open"
            assert metrics["breaker_transitions_total"] >= 1
            assert metrics["shed_total"] >= 1

            # fix the engine and elapse the cooldown; the next request is the
            # half-open probe — success closes the breaker
            engine.broken = False
            breaker._opened_at -= 61.0
            probe = await client.post("/detect", json=payload)
            assert probe.status == 200
            assert isinstance((await probe.json())["images"][0].get("detections"), list)

            health = await client.get("/healthz")
            assert health.status == 200
            metrics = await (await client.get("/metrics")).json()
            assert metrics["breaker_state"] == "closed"

    asyncio.run(run())


def test_server_queue_full_sheds_429():
    """Overload at the HTTP edge: with the engine wedged and the queue full,
    a fully-shed request answers 429 + Retry-After instead of buffering."""
    engine = FakeEngine()
    batcher = MicroBatcher(
        engine,
        max_batch=1,
        max_delay_ms=1.0,
        max_in_flight=1,
        max_queue=1,
        batch_timeout_ms=0.0,
        breaker=CircuitBreaker(threshold=100, metrics=engine.metrics),
    )
    detector = AmenitiesDetector(engine, batcher, _client_returning_image())

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            payload = {"image_urls": ["http://e.com/a.jpg"]}
            with faults.inject(engine_hang_s=10.0) as plan:
                first = asyncio.create_task(client.post("/detect", json=payload))
                await asyncio.sleep(0.1)  # r1 now wedged in the engine
                second = asyncio.create_task(client.post("/detect", json=payload))
                await asyncio.sleep(0.1)  # r2 drained, held by the pump at the slot
                third = asyncio.create_task(client.post("/detect", json=payload))
                await asyncio.sleep(0.1)  # r3 occupies the queue (depth 1)
                fourth = await client.post("/detect", json=payload)
                assert fourth.status == 429
                assert "Retry-After" in fourth.headers
                plan.release.set()
                r1, r2, r3 = await asyncio.gather(first, second, third)
                assert {r1.status, r2.status, r3.status} == {200}
            snap = engine.metrics.snapshot()
            assert snap["shed_total"] >= 1

    asyncio.run(run())


def test_server_drain_hook():
    """/drain (k8s preStop): flush, then stop admitting with 503; readiness
    goes unready while liveness stays green."""
    detector, _ = _detector()

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            payload = {"image_urls": ["http://e.com/a.jpg"]}
            ok = await client.post("/detect", json=payload)
            assert ok.status == 200

            drained = await client.post("/drain")
            assert drained.status == 200
            body = await drained.json()
            assert body["status"] == "drained"
            assert body["queued_failed"] == 0

            shed = await client.post("/detect", json=payload)
            assert shed.status == 503
            health = await client.get("/healthz")
            assert health.status == 503
            assert (await health.json())["draining"] is True
            live = await client.get("/livez")
            assert live.status == 200

            metrics = await (await client.get("/metrics")).json()
            assert metrics["draining"] is True

            again = await client.post("/drain")  # idempotent
            assert again.status == 200

    asyncio.run(run())


def test_faults_env_activation(monkeypatch):
    monkeypatch.setenv(faults.FAULTS_ENV, "fetch_error=2,engine_hang_s=1.5")
    plan = faults.maybe_activate_from_env()
    try:
        assert plan.fetch_error == 2
        assert plan.engine_hang_s == 1.5
        assert faults.active() is plan
    finally:
        faults._active = None
    monkeypatch.setenv(faults.FAULTS_ENV, "bogus_fault=1")
    with pytest.raises(ValueError):
        faults.maybe_activate_from_env()
