"""Chaos suite (ISSUE 1 + ISSUE 4 acceptance): under injected fetch
failures, engine exceptions, and a simulated engine hang, no request awaits
forever — every caller gets a result, a structured error, or a shed
response within its deadline, and the pump keeps serving subsequent
traffic. The engine-fault-domain tests (ISSUE 4) add: a poisonous item is
bisect-isolated so co-batched innocents succeed and the breaker stays
closed; an injected device OOM recovers via the bucket-downgrade retry with
zero client-visible errors; an injected dead shard under dp=2 rebuilds the
engine at dp=1 in place (no process exit) with /healthz reporting the
degradation. Faults come from spotter_tpu/testing/faults.py, the same
harness a chaos-staging server arms via SPOTTER_TPU_FAULTS."""

import asyncio
import time
from io import BytesIO
from unittest.mock import AsyncMock

import httpx
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

from spotter_tpu.engine.batcher import BatchTimeoutError, MicroBatcher
from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.schemas import DetectionErrorResult, DetectionSuccessResult
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.resilience import (
    CircuitBreaker,
    Deadline,
    DeadlineExceededError,
)
from spotter_tpu.serving.standalone import make_app
from spotter_tpu.testing import faults

DETS = [{"label": "tv", "score": 0.9, "box": [1.0, 2.0, 20.0, 30.0]}]


class FakeEngine:
    def __init__(self, detections=DETS):
        self.detections = detections
        self.metrics = Metrics()
        self.batch_buckets = (1, 2, 4)
        self.calls = []
        self.broken = False

    def detect(self, images):
        if self.broken:
            raise RuntimeError("engine down")
        self.calls.append(len(images))
        return [list(self.detections) for _ in images]


@pytest.fixture(autouse=True)
def _zero_retry_backoff(monkeypatch):
    import spotter_tpu.serving.detector as det_mod

    monkeypatch.setattr(det_mod, "FETCH_RETRY_WAIT_MIN_S", 0.0)
    monkeypatch.setattr(det_mod, "FETCH_RETRY_WAIT_MAX_S", 0.0)


def _image_bytes(w=32, h=32):
    img = Image.fromarray(np.full((h, w, 3), 128, np.uint8))
    buf = BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


def _client_returning_image():
    resp = AsyncMock()
    resp.content = _image_bytes()
    resp.raise_for_status = lambda: None
    client = AsyncMock(spec=httpx.AsyncClient)
    client.get.return_value = resp
    return client


def _img():
    return Image.fromarray(np.zeros((8, 8, 3), np.uint8))


def _detector(engine=None, **batcher_kwargs):
    engine = engine or FakeEngine()
    batcher_kwargs.setdefault("max_delay_ms", 1.0)
    batcher_kwargs.setdefault(
        "breaker", CircuitBreaker(threshold=100, metrics=engine.metrics)
    )
    batcher = MicroBatcher(engine, **batcher_kwargs)
    return AmenitiesDetector(engine, batcher, _client_returning_image()), engine


def test_fetch_faults_contained_and_pump_survives():
    detector, engine = _detector()

    async def run():
        with faults.inject(fetch_error=-1):
            broken = await detector.detect(
                {"image_urls": ["http://e.com/a.jpg", "http://e.com/b.jpg"]}
            )
        healthy = await detector.detect({"image_urls": ["http://e.com/c.jpg"]})
        await detector.batcher.stop()
        return broken, healthy

    broken, healthy = asyncio.run(run())
    assert all(isinstance(r, DetectionErrorResult) for r in broken.images)
    assert all(r.error.startswith("HTTP Error:") for r in broken.images)
    (ok,) = healthy.images
    assert isinstance(ok, DetectionSuccessResult)


def test_malformed_image_contained():
    detector, _ = _detector()

    async def run():
        with faults.inject(malformed_image=1):
            broken = await detector.detect({"image_urls": ["http://e.com/a.jpg"]})
        healthy = await detector.detect({"image_urls": ["http://e.com/b.jpg"]})
        await detector.batcher.stop()
        return broken, healthy

    broken, healthy = asyncio.run(run())
    (bad,) = broken.images
    assert isinstance(bad, DetectionErrorResult)
    assert bad.error.startswith("Processing Error:")
    assert isinstance(healthy.images[0], DetectionSuccessResult)


def test_engine_exception_fails_only_its_batch():
    detector, engine = _detector()

    async def run():
        with faults.inject(engine_error=1):
            broken = await detector.detect({"image_urls": ["http://e.com/a.jpg"]})
        healthy = await detector.detect({"image_urls": ["http://e.com/b.jpg"]})
        await detector.batcher.stop()
        return broken, healthy

    broken, healthy = asyncio.run(run())
    (bad,) = broken.images
    assert isinstance(bad, DetectionErrorResult)
    assert "injected engine failure" in bad.error
    assert isinstance(healthy.images[0], DetectionSuccessResult)
    assert engine.metrics.snapshot()["errors_total"] >= 1


def test_engine_hang_watchdog_frees_slot_and_pump_survives():
    """A wedged engine call must fail its futures via the watchdog and
    release its in-flight slot — not deadlock the pump forever."""
    engine = FakeEngine()
    batcher = MicroBatcher(
        engine,
        max_batch=1,
        max_delay_ms=1.0,
        max_in_flight=1,
        batch_timeout_ms=200.0,
        breaker=CircuitBreaker(threshold=100, metrics=engine.metrics),
    )

    async def run():
        t0 = time.monotonic()
        with faults.inject(engine_hang_s=30.0) as plan:
            with pytest.raises(BatchTimeoutError):
                await batcher.submit(_img(), deadline=Deadline.after(5.0))
            hung_for = time.monotonic() - t0
            plan.release.set()  # un-wedge the orphaned worker thread
        result = await batcher.submit(_img())
        await batcher.stop()
        return hung_for, result

    hung_for, result = asyncio.run(run())
    assert hung_for < 2.0  # watchdog (200 ms), not the 30 s hang or 5 s deadline
    assert result == DETS
    snap = engine.metrics.snapshot()
    assert snap["batch_timeouts_total"] == 1


def test_deadline_bounds_slow_fetch():
    detector, _ = _detector()

    async def run():
        t0 = time.monotonic()
        with faults.inject(fetch_delay_s=5.0):
            resp = await detector.detect(
                {"image_urls": ["http://e.com/a.jpg"]},
                deadline=Deadline.after(0.15),
            )
        elapsed = time.monotonic() - t0
        await detector.batcher.stop()
        return resp, elapsed

    resp, elapsed = asyncio.run(run())
    (r,) = resp.images
    assert isinstance(r, DetectionErrorResult)
    assert r.error.startswith("Deadline exceeded:")
    assert elapsed < 1.0  # bounded by the deadline, not the injected delay


def test_deadline_bounds_hung_device_call():
    engine = FakeEngine()
    batcher = MicroBatcher(
        engine,
        max_batch=1,
        max_delay_ms=1.0,
        batch_timeout_ms=0.0,  # watchdog off: the deadline alone must bound it
        breaker=CircuitBreaker(threshold=100, metrics=engine.metrics),
    )

    async def run():
        with faults.inject(engine_hang_s=10.0) as plan:
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceededError):
                await batcher.submit(_img(), deadline=Deadline.after(0.15))
            elapsed = time.monotonic() - t0
            plan.release.set()
        await batcher.stop()
        return elapsed

    elapsed = asyncio.run(run())
    assert elapsed < 1.0
    assert engine.metrics.snapshot()["deadline_exceeded_total"] == 1


def test_server_breaker_healthz_cycle():
    """Acceptance: /healthz 503 while the breaker is open, 200 again after a
    successful half-open probe, transitions visible in /metrics."""
    engine = FakeEngine()
    engine.broken = True
    # cooldown long enough that the shed-while-open assertions can't race it;
    # the test elapses it deterministically by rewinding _opened_at
    breaker = CircuitBreaker(threshold=2, cooldown_s=60.0, metrics=engine.metrics)
    batcher = MicroBatcher(engine, max_delay_ms=1.0, breaker=breaker)
    detector = AmenitiesDetector(engine, batcher, _client_returning_image())

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            payload = {"image_urls": ["http://e.com/a.jpg"]}
            # two engine-failure batches: contained per-image (HTTP 200) but
            # counted by the breaker, which trips at threshold 2
            for _ in range(2):
                resp = await client.post("/detect", json=payload)
                assert resp.status == 200
                body = await resp.json()
                assert "Processing Error" in body["images"][0]["error"]
            assert breaker.state == CircuitBreaker.OPEN

            health = await client.get("/healthz")
            assert health.status == 503
            assert (await health.json())["breaker"] == "open"
            live = await client.get("/livez")
            assert live.status == 200  # liveness is separate from readiness

            shed = await client.post("/detect", json=payload)
            assert shed.status == 503
            assert "Retry-After" in shed.headers

            metrics = await (await client.get("/metrics")).json()
            assert metrics["breaker_state"] == "open"
            assert metrics["breaker_transitions_total"] >= 1
            assert metrics["shed_total"] >= 1

            # fix the engine and elapse the cooldown; the next request is the
            # half-open probe — success closes the breaker
            engine.broken = False
            breaker._opened_at -= 61.0
            probe = await client.post("/detect", json=payload)
            assert probe.status == 200
            assert isinstance((await probe.json())["images"][0].get("detections"), list)

            health = await client.get("/healthz")
            assert health.status == 200
            metrics = await (await client.get("/metrics")).json()
            assert metrics["breaker_state"] == "closed"

    asyncio.run(run())


def test_server_queue_full_sheds_429():
    """Overload at the HTTP edge: with the engine wedged and the queue full,
    a fully-shed request answers 429 + Retry-After instead of buffering."""
    engine = FakeEngine()
    batcher = MicroBatcher(
        engine,
        max_batch=1,
        max_delay_ms=1.0,
        max_in_flight=1,
        max_queue=1,
        batch_timeout_ms=0.0,
        breaker=CircuitBreaker(threshold=100, metrics=engine.metrics),
    )
    detector = AmenitiesDetector(engine, batcher, _client_returning_image())

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            payload = {"image_urls": ["http://e.com/a.jpg"]}
            with faults.inject(engine_hang_s=10.0) as plan:
                first = asyncio.create_task(client.post("/detect", json=payload))
                await asyncio.sleep(0.1)  # r1 now wedged in the engine
                second = asyncio.create_task(client.post("/detect", json=payload))
                await asyncio.sleep(0.1)  # r2 drained, held by the pump at the slot
                third = asyncio.create_task(client.post("/detect", json=payload))
                await asyncio.sleep(0.1)  # r3 occupies the queue (depth 1)
                fourth = await client.post("/detect", json=payload)
                assert fourth.status == 429
                assert "Retry-After" in fourth.headers
                plan.release.set()
                r1, r2, r3 = await asyncio.gather(first, second, third)
                assert {r1.status, r2.status, r3.status} == {200}
            snap = engine.metrics.snapshot()
            assert snap["shed_total"] >= 1

    asyncio.run(run())


def test_server_drain_hook():
    """/drain (k8s preStop): flush, then stop admitting with 503; readiness
    goes unready while liveness stays green."""
    detector, _ = _detector()

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            payload = {"image_urls": ["http://e.com/a.jpg"]}
            ok = await client.post("/detect", json=payload)
            assert ok.status == 200

            drained = await client.post("/drain")
            assert drained.status == 200
            body = await drained.json()
            assert body["status"] == "drained"
            assert body["queued_failed"] == 0

            shed = await client.post("/detect", json=payload)
            assert shed.status == 503
            health = await client.get("/healthz")
            assert health.status == 503
            assert (await health.json())["draining"] is True
            live = await client.get("/livez")
            assert live.status == 200

            metrics = await (await client.get("/metrics")).json()
            assert metrics["draining"] is True

            again = await client.post("/drain")  # idempotent
            assert again.status == 200

    asyncio.run(run())


def test_faults_env_activation(monkeypatch):
    monkeypatch.setenv(
        faults.FAULTS_ENV, "fetch_error=2,engine_hang_s=1.5,engine_oom=1,shard_dead=3"
    )
    plan = faults.maybe_activate_from_env()
    try:
        assert plan.fetch_error == 2
        assert plan.engine_hang_s == 1.5
        assert plan.engine_oom == 1
        assert plan.shard_dead == 3
        assert faults.active() is plan
    finally:
        faults._active = None
    monkeypatch.setenv(faults.FAULTS_ENV, "bogus_fault=1")
    with pytest.raises(ValueError):
        faults.maybe_activate_from_env()


# --- engine fault domain (ISSUE 4) -------------------------------------------


def test_poison_item_isolated_innocents_succeed_breaker_closed():
    """Acceptance: a 1-of-8 poison_item injection under concurrent load —
    every non-poison request in the batch succeeds, exactly the poison
    request fails with PoisonImageError, and the breaker stays CLOSED."""
    from spotter_tpu.engine.errors import PoisonImageError

    engine = FakeEngine()
    engine.batch_buckets = (1, 2, 4, 8)
    breaker = CircuitBreaker(threshold=2, metrics=engine.metrics)
    batcher = MicroBatcher(engine, max_batch=8, max_delay_ms=100.0, breaker=breaker)
    images = [_img() for _ in range(8)]
    faults.poison_image(images[3])

    async def run():
        with faults.inject(poison_item=1):
            results = await asyncio.gather(
                *(batcher.submit(im) for im in images), return_exceptions=True
            )
        await batcher.stop()
        return results

    results = asyncio.run(run())
    poison_failures = [r for r in results if isinstance(r, PoisonImageError)]
    successes = [r for r in results if not isinstance(r, BaseException)]
    assert len(poison_failures) == 1 and isinstance(results[3], PoisonImageError)
    assert len(successes) == 7 and all(r == DETS for r in successes)
    assert breaker.state == CircuitBreaker.CLOSED
    snap = engine.metrics.snapshot()
    assert snap["poison_isolated_total"] == 1
    assert snap["batch_retries_total"] >= 1
    assert snap["errors_total"] == 1


def test_isolated_poison_never_opens_breaker_but_dead_engine_does():
    """Satellite: poison isolation x CircuitBreaker interplay. Repeated
    isolated poisons must not open the breaker; a genuinely failing engine
    (every co-batched item fails, splits included) still must."""
    engine = FakeEngine()
    engine.batch_buckets = (1, 2, 4)
    breaker = CircuitBreaker(threshold=2, metrics=engine.metrics)
    batcher = MicroBatcher(engine, max_batch=4, max_delay_ms=100.0, breaker=breaker)

    async def poison_round():
        images = [_img() for _ in range(4)]
        faults.poison_image(images[0])
        with faults.inject(poison_item=1):
            return await asyncio.gather(
                *(batcher.submit(im) for im in images), return_exceptions=True
            )

    async def run():
        # threshold-2 breaker survives 3 consecutive poisoned batches …
        for _ in range(3):
            results = await poison_round()
            assert sum(1 for r in results if isinstance(r, BaseException)) == 1
            assert breaker.state == CircuitBreaker.CLOSED
        # … but an engine that fails every item (bisect can't find an
        # innocent) trips it at the threshold
        engine.broken = True
        for _ in range(2):
            with pytest.raises(RuntimeError, match="engine down"):
                await batcher.submit(_img())
        assert breaker.state == CircuitBreaker.OPEN
        await batcher.stop()

    asyncio.run(run())
    snap = engine.metrics.snapshot()
    assert snap["poison_isolated_total"] == 3


def test_poison_isolation_disabled_fails_whole_batch():
    """SPOTTER_TPU_POISON_MAX_SPLITS<=0 restores all-or-nothing batches —
    and the whole-batch failure counts against the breaker."""
    engine = FakeEngine()
    engine.batch_buckets = (1, 2, 4)
    breaker = CircuitBreaker(threshold=1, metrics=engine.metrics)
    batcher = MicroBatcher(
        engine, max_batch=4, max_delay_ms=100.0, breaker=breaker, poison_max_splits=0
    )
    images = [_img() for _ in range(4)]
    faults.poison_image(images[1])

    async def run():
        with faults.inject(poison_item=1):
            results = await asyncio.gather(
                *(batcher.submit(im) for im in images), return_exceptions=True
            )
        await batcher.stop()
        return results

    results = asyncio.run(run())
    assert all(isinstance(r, RuntimeError) for r in results)
    assert breaker.state == CircuitBreaker.OPEN
    assert engine.metrics.snapshot()["poison_isolated_total"] == 0


@pytest.fixture(scope="module")
def tiny_built():
    """A real (tiny) RT-DETR BuiltDetector: the OOM-downgrade and dead-shard
    scenarios need the real InferenceEngine classify/recover path, which the
    FakeEngine can't exercise."""
    import jax

    from spotter_tpu.engine.engine import BuiltDetector
    from spotter_tpu.models.rtdetr import RTDetrDetector
    from spotter_tpu.models.zoo import tiny_rtdetr_config
    from spotter_tpu.ops.preprocess import PreprocessSpec

    cfg = tiny_rtdetr_config()
    module = RTDetrDetector(cfg)
    params = module.init(
        jax.random.PRNGKey(0), np.zeros((1, 64, 64, 3), np.float32)
    )["params"]
    return BuiltDetector(
        model_name="tiny-chaos",
        module=module,
        params=params,
        preprocess_spec=PreprocessSpec(mode="fixed", size=(64, 64)),
        postprocess="sigmoid_topk",
        id2label=cfg.id2label_dict,
        num_top_queries=10,
    )


def test_engine_oom_once_downgrades_bucket_zero_client_errors(tiny_built):
    """Acceptance: an engine_oom_once injection at the largest bucket
    recovers via the bucket-downgrade retry — the halves land in the
    next-smaller bucket — with zero client-visible errors."""
    from spotter_tpu.engine.engine import InferenceEngine

    engine = InferenceEngine(tiny_built, threshold=0.0, batch_buckets=(2, 4))
    breaker = CircuitBreaker(threshold=2, metrics=engine.metrics)
    batcher = MicroBatcher(engine, max_batch=4, max_delay_ms=100.0, breaker=breaker)
    rng = np.random.default_rng(7)
    images = [
        Image.fromarray(rng.integers(0, 255, (48, 64, 3), dtype=np.uint8))
        for _ in range(4)
    ]

    async def run():
        with faults.inject(engine_oom=1):
            results = await asyncio.gather(*(batcher.submit(im) for im in images))
        await batcher.stop()
        return results

    results = asyncio.run(run())
    assert len(results) == 4
    assert all(isinstance(r, list) and len(r) > 0 for r in results)
    snap = engine.metrics.snapshot()
    assert snap["batch_retries_total"] >= 1
    assert snap["errors_total"] == 0
    assert breaker.state == CircuitBreaker.CLOSED


def test_shard_dead_rebuilds_degraded_dp1_no_process_exit(tiny_built):
    """Acceptance (dp=2 virtual devices): injected shard_dead -> the engine
    rebuilds at dp=1 WITHOUT a process exit, /healthz reports the
    degradation, and post-rebuild requests succeed."""
    import jax

    from spotter_tpu.engine.engine import InferenceEngine
    from spotter_tpu.parallel.mesh import make_mesh

    devs = jax.devices()[:2]
    mesh = make_mesh(dp=2, tp=1, devices=devs)
    engine = InferenceEngine(
        tiny_built, threshold=0.0, batch_buckets=(2, 4), mesh=mesh
    )
    assert engine.dp == 2
    breaker = CircuitBreaker(threshold=10, metrics=engine.metrics)
    batcher = MicroBatcher(engine, max_delay_ms=5.0, breaker=breaker)
    detector = AmenitiesDetector(engine, batcher, _client_returning_image())
    exit_codes: list[int] = []

    async def run():
        app = make_app(detector=detector, fatal_exit_cb=exit_codes.append)
        async with TestClient(TestServer(app)) as client:
            payload = {"image_urls": ["http://e.com/a.jpg"]}
            ok = await client.post("/detect", json=payload)
            assert ok.status == 200
            health = await (await client.get("/healthz")).json()
            assert health["dp"] == 2 and health["dp_degraded"] is None

            with faults.inject(shard_dead=devs[1].id):
                # this request's batch dies with the shard; its error is
                # contained per-image (the pool layer replays such failures)
                broken = await client.post("/detect", json=payload)
                assert broken.status == 200
                body = await broken.json()
                assert "error" in body["images"][0]

                # the batcher's degraded rebuild runs in the batch task;
                # wait for the generation bump instead of sleeping blind
                for _ in range(600):
                    if engine.generation >= 1:
                        break
                    await asyncio.sleep(0.05)
                assert engine.generation >= 1
                assert engine.dp == 1

                after = await client.post("/detect", json=payload)
                assert after.status == 200
                assert "labeled_image_base64" in (await after.json())["images"][0]

                health = await client.get("/healthz")
                assert health.status == 200  # degraded but READY (still serving)
                hbody = await health.json()
                assert hbody["status"] == "degraded"
                assert hbody["dp_degraded"] == {"from": 2, "to": 1}
                startup = await (await client.get("/startupz")).json()
                assert startup["state"] == "ready"
                metrics = await (await client.get("/metrics")).json()
                assert metrics["engine_rebuilds_total"] == 1
                assert metrics["fatal_engine_errors_total"] >= 1
                assert metrics["dp_degraded"] == {"from": 2, "to": 1}

    asyncio.run(run())
    assert exit_codes == []  # degraded in place, never exited
