"""Detector behavior tests — the analog of the reference's test_serve.py suite:
plain class, fake model (here: fake engine), no serving runtime required
(test_serve.py:32 tests the undecorated class the same way)."""

import asyncio
import base64
from io import BytesIO
from unittest.mock import AsyncMock

import httpx
import numpy as np
import pytest
from PIL import Image

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.schemas import DetectionErrorResult, DetectionSuccessResult
from spotter_tpu.serving.detector import AmenitiesDetector


class FakeEngine:
    """Stands in for InferenceEngine: canned per-image detections."""

    def __init__(self, detections):
        self.detections = detections
        self.metrics = Metrics()
        self.batch_buckets = (1, 2, 4)
        self.calls = []

    def detect(self, images):
        self.calls.append(len(images))
        return [list(self.detections) for _ in images]


@pytest.fixture(autouse=True)
def _zero_retry_backoff(monkeypatch):
    """Keep the 3-attempt retry CONTRACT but not its 4-10 s sleeps: the tests
    assert attempt counts, not wall-clock backoff."""
    import spotter_tpu.serving.detector as det_mod

    monkeypatch.setattr(det_mod, "FETCH_RETRY_WAIT_MIN_S", 0.0)
    monkeypatch.setattr(det_mod, "FETCH_RETRY_WAIT_MAX_S", 0.0)


def _image_bytes(w=64, h=48):
    img = Image.fromarray(np.full((h, w, 3), 200, np.uint8))
    buf = BytesIO()
    img.save(buf, format="JPEG")
    return buf.getvalue()


def _detector(detections, fetch=None):
    engine = FakeEngine(detections)
    client = AsyncMock(spec=httpx.AsyncClient)
    if fetch is not None:
        client.get.side_effect = fetch
    else:
        resp = AsyncMock()
        resp.content = _image_bytes()
        resp.raise_for_status = lambda: None
        client.get.return_value = resp
    return AmenitiesDetector(engine, MicroBatcher(engine, max_delay_ms=1.0), client), engine


def test_success_remaps_labels_and_draws():
    dets = [
        {"label": "tv", "score": 0.9, "box": [1.0, 2.0, 20.0, 30.0]},
        {"label": "couch", "score": 0.8, "box": [5.0, 5.0, 40.0, 40.0]},
        {"label": "remote", "score": 0.9, "box": [0.0, 0.0, 3.0, 3.0]},  # irrelevant
    ]
    detector, engine = _detector(dets)

    async def run():
        return await detector.detect({"image_urls": ["http://example.com/a.jpg"]})

    resp = asyncio.run(run())
    assert resp.amenities_description == "The property contains: TV, sofa."
    (img_result,) = resp.images
    assert isinstance(img_result, DetectionSuccessResult)
    labels = [d.label for d in img_result.detections]
    assert labels == ["TV", "sofa"]  # remapped per AMENITIES_MAPPING; remote dropped
    assert img_result.detections[0].box == [1.0, 2.0, 20.0, 30.0]
    # labeled image is a decodable JPEG
    decoded = base64.b64decode(img_result.labeled_image_base64)
    Image.open(BytesIO(decoded)).verify()


def test_irrelevant_only_still_encodes_image():
    detector, _ = _detector([{"label": "remote", "score": 0.9, "box": [0, 0, 3, 3]}])

    async def run():
        return await detector.detect({"image_urls": ["http://example.com/a.jpg"]})

    resp = asyncio.run(run())
    assert resp.amenities_description == "No relevant amenities detected."
    (img_result,) = resp.images
    assert img_result.detections == []
    assert len(img_result.labeled_image_base64) > 0


def test_fetch_http_error_contained():
    def fail(url):
        raise httpx.ConnectError("boom")

    detector, _ = _detector([], fetch=fail)

    async def run():
        return await detector.detect(
            {"image_urls": ["http://bad.example.com/a.jpg", "http://bad.example.com/b.jpg"]}
        )

    resp = asyncio.run(run())
    assert all(isinstance(r, DetectionErrorResult) for r in resp.images)
    assert all(r.error.startswith("HTTP Error:") for r in resp.images)
    assert resp.amenities_description == "No relevant amenities detected."


def test_processing_error_contained_with_traceback():
    resp_ok = AsyncMock()
    resp_ok.content = b"not an image"
    resp_ok.raise_for_status = lambda: None

    detector, _ = _detector([], fetch=lambda url: resp_ok)

    async def run():
        return await detector.detect({"image_urls": ["http://example.com/a.jpg"]})

    resp = asyncio.run(run())
    (result,) = resp.images
    assert isinstance(result, DetectionErrorResult)
    assert result.error.startswith("Processing Error:")
    assert "Traceback" in result.error


def test_one_bad_url_does_not_fail_batch():
    calls = {"n": 0}

    def mixed(url):
        calls["n"] += 1
        if "bad" in url:
            raise httpx.ConnectError("down")
        resp = AsyncMock()
        resp.content = _image_bytes()
        resp.raise_for_status = lambda: None
        return resp

    detector, _ = _detector(
        [{"label": "oven", "score": 0.9, "box": [1, 1, 9, 9]}], fetch=mixed
    )

    async def run():
        return await detector.detect(
            {"image_urls": ["http://ok.example.com/a.jpg", "http://bad.example.com/b.jpg"]}
        )

    resp = asyncio.run(run())
    ok, bad = resp.images
    assert isinstance(ok, DetectionSuccessResult)
    assert isinstance(bad, DetectionErrorResult)
    assert resp.amenities_description == "The property contains: oven."
    # retry policy: bad URL fetched 3 times (serve.py:84-88), good once
    assert calls["n"] == 4


def test_microbatcher_batches_concurrent_requests():
    engine = FakeEngine([{"label": "tv", "score": 0.9, "box": [0, 0, 5, 5]}])
    batcher = MicroBatcher(engine, max_batch=4, max_delay_ms=50.0)
    img = Image.fromarray(np.zeros((8, 8, 3), np.uint8))

    async def run():
        results = await asyncio.gather(*[batcher.submit(img) for _ in range(4)])
        await batcher.stop()
        return results

    results = asyncio.run(run())
    assert len(results) == 4
    # all four went through one device call
    assert engine.calls == [4] or sum(engine.calls) == 4


def test_stop_fails_batch_held_at_slot_acquire():
    """stop() while the pump holds a drained batch (waiting for an in-flight
    slot) must fail that batch's futures, not strand them forever."""
    import threading

    release = threading.Event()

    class BlockingEngine(FakeEngine):
        def detect(self, images):
            release.wait(timeout=10.0)
            return super().detect(images)

    engine = BlockingEngine([{"label": "tv", "score": 0.9, "box": [0, 0, 5, 5]}])
    batcher = MicroBatcher(engine, max_batch=1, max_delay_ms=1.0, max_in_flight=1)
    img = Image.fromarray(np.zeros((8, 8, 3), np.uint8))

    async def run():
        first = asyncio.create_task(batcher.submit(img))
        await asyncio.sleep(0.1)  # first batch now blocks inside detect()
        second = asyncio.create_task(batcher.submit(img))
        await asyncio.sleep(0.1)  # pump drained it and waits on the slot
        stop = asyncio.create_task(batcher.stop())
        await asyncio.sleep(0.05)
        release.set()  # let the in-flight batch finish so stop() completes
        await stop
        return first, second

    first, second = asyncio.run(run())
    assert first.result() == [{"label": "tv", "score": 0.9, "box": [0, 0, 5, 5]}]
    with pytest.raises(RuntimeError, match="MicroBatcher stopped"):
        second.result()


def test_validation_error_rejects_bad_payload():
    detector, _ = _detector([])

    async def run():
        with pytest.raises(Exception):
            await detector.detect({"image_urls": ["not a url"]})

    asyncio.run(run())


def _status_error_fetch(status: int, counter: dict):
    """A fetch side_effect raising a real httpx.HTTPStatusError."""

    def fail(url):
        counter["n"] += 1
        req = httpx.Request("GET", url)
        resp = httpx.Response(status, request=req)
        raise httpx.HTTPStatusError(f"{status}", request=req, response=resp)

    return fail


def test_404_fails_fast_without_retries():
    """Satellite (ISSUE 4): a deterministic 4xx must not be retried through
    3 attempts of backoff — one fetch, one structured error."""
    calls = {"n": 0}
    detector, _ = _detector([], fetch=_status_error_fetch(404, calls))

    async def run():
        return await detector.detect({"image_urls": ["http://example.com/gone.jpg"]})

    resp = asyncio.run(run())
    (r,) = resp.images
    assert isinstance(r, DetectionErrorResult)
    assert r.error.startswith("HTTP Error:")
    assert calls["n"] == 1  # NOT 3: non-retryable status


def test_5xx_still_retried_three_times():
    calls = {"n": 0}
    detector, _ = _detector([], fetch=_status_error_fetch(503, calls))

    async def run():
        return await detector.detect({"image_urls": ["http://example.com/busy.jpg"]})

    resp = asyncio.run(run())
    (r,) = resp.images
    assert isinstance(r, DetectionErrorResult)
    assert calls["n"] == 3  # transient status keeps the reference retry contract


def test_fetch_max_bytes_cap_rejects_without_retry(monkeypatch):
    """SPOTTER_TPU_FETCH_MAX_BYTES: an oversized body is a typed, fast,
    non-retried per-image error — not a host-memory liability."""
    monkeypatch.setenv("SPOTTER_TPU_FETCH_MAX_BYTES", "64")
    calls = {"n": 0}

    def big(url):
        calls["n"] += 1
        resp = AsyncMock()
        resp.content = b"x" * 1024
        resp.raise_for_status = lambda: None
        return resp

    detector, _ = _detector([], fetch=big)

    async def run():
        return await detector.detect({"image_urls": ["http://example.com/huge.jpg"]})

    resp = asyncio.run(run())
    (r,) = resp.images
    assert isinstance(r, DetectionErrorResult)
    assert r.error.startswith("Fetch Error:")
    assert "SPOTTER_TPU_FETCH_MAX_BYTES" in r.error
    assert calls["n"] == 1


def test_decode_bomb_guard_is_per_image_error(monkeypatch):
    """SPOTTER_TPU_MAX_IMAGE_PIXELS rejects a decode bomb before convert()
    decodes it; co-requested small images still succeed."""
    monkeypatch.setenv("SPOTTER_TPU_MAX_IMAGE_PIXELS", "1000")  # 64x48 > 1000 px

    def mixed(url):
        resp = AsyncMock()
        # "bomb" is only big by pixel count; tiny stays under the cap
        resp.content = _image_bytes() if "bomb" in url else _image_bytes(w=20, h=20)
        resp.raise_for_status = lambda: None
        return resp

    detector, _ = _detector(
        [{"label": "oven", "score": 0.9, "box": [1, 1, 9, 9]}], fetch=mixed
    )

    async def run():
        return await detector.detect(
            {
                "image_urls": [
                    "http://example.com/bomb.jpg",
                    "http://example.com/ok.jpg",
                ]
            }
        )

    resp = asyncio.run(run())
    bomb, ok = resp.images
    assert isinstance(bomb, DetectionErrorResult)
    assert "SPOTTER_TPU_MAX_IMAGE_PIXELS" in bomb.error
    assert isinstance(ok, DetectionSuccessResult)
