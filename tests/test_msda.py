"""Fused MSDA sampling op: XLA/Pallas parity, both methods, gradients.

The op (spotter_tpu/ops/msda.py) replaces the per-level grid-sample chain.
Reference semantics here are the original formulation via
`grid_sample_bilinear_nhwc` (torch grid_sample parity, zeros padding,
align_corners=False) — the same math the torch lineage's CUDA sampler
implements (HF modeling_rt_detr_v2 multi_scale_deformable_attention_v2).
Pallas runs in interpret mode on the CPU test mesh (SURVEY.md §4.4).
"""

import os
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spotter_tpu.models.layers import grid_sample_bilinear_nhwc
import spotter_tpu.ops.msda as M
from spotter_tpu.ops.msda import (
    MSDA_ENV,
    deformable_sampling,
    msda_backend,
    prepare_msda_gather,
    pallas_deformable_sampling,
    xla_deformable_sampling,
)

SHAPES = ((8, 8), (4, 4), (2, 2))
B, Q, H, HD, P = 2, 7, 4, 8, 3
LP = len(SHAPES) * P
S = sum(h * w for h, w in SHAPES)


def _random_inputs(seed=0):
    rng = np.random.default_rng(seed)
    value = rng.standard_normal((B, S, H, HD)).astype(np.float32)
    # locations mostly inside [0,1] with some outside to exercise zero-padding
    loc = rng.uniform(-0.2, 1.2, (B, Q, H, LP, 2)).astype(np.float32)
    attn = jax.nn.softmax(
        jnp.asarray(rng.standard_normal((B, Q, H, LP)).astype(np.float32)), axis=-1
    )
    return jnp.asarray(value), jnp.asarray(loc), np.asarray(attn)


def _reference(value, loc, attn):
    """Original per-level grid-sample formulation (pre-fusion module code)."""
    sampled = []
    start = 0
    for lvl, (h, w) in enumerate(SHAPES):
        v = value[:, start : start + h * w]
        start += h * w
        v = v.transpose(0, 2, 1, 3).reshape(B * H, h, w, HD)
        g = loc[:, :, :, lvl * P : (lvl + 1) * P, :]
        g = g.transpose(0, 2, 1, 3, 4).reshape(B * H, Q, P, 2)
        sampled.append(grid_sample_bilinear_nhwc(v, 2.0 * g - 1.0))
    sampled = jnp.concatenate(sampled, axis=2)
    aw = jnp.asarray(attn).transpose(0, 2, 1, 3).reshape(B * H, Q, LP, 1)
    out = (sampled * aw).sum(axis=2)
    return out.reshape(B, H, Q, HD).transpose(0, 2, 1, 3).reshape(B, Q, H * HD)


def test_xla_path_matches_grid_sample_reference():
    value, loc, attn = _random_inputs()
    got = deformable_sampling(value, loc, attn, SHAPES, P, backend="xla")
    ref = _reference(value, loc, attn)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("backend", ["pallas", "pallas_sep", "pallas_gather"])
def test_pallas_interpret_matches_xla(backend):
    value, loc, attn = _random_inputs(1)
    got = deformable_sampling(
        value, loc, attn, SHAPES, P, backend=backend, interpret=True
    )
    ref = deformable_sampling(value, loc, attn, SHAPES, P, backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_finer_level0_tile_matches_xla(monkeypatch):
    """SPOTTER_TPU_MSDA_STILE0: a finer tile on the densest level is a pure
    performance knob — identical results AND gradients (the VJP reference's
    per-level offset arithmetic) vs the uniform-tile/xla paths."""
    import spotter_tpu.ops.msda as M

    monkeypatch.setattr(M, "S_TILE", 32)
    monkeypatch.setattr(M, "S_TILE0", 16)
    value, loc, attn = _random_inputs(5)
    got = deformable_sampling(
        value, loc, attn, SHAPES, P, backend="pallas", interpret=True
    )
    ref = deformable_sampling(value, loc, attn, SHAPES, P, backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)

    def loss(backend):
        def f(v, a):
            out = deformable_sampling(
                v, loc, a, SHAPES, P, backend=backend,
                interpret=backend != "xla",
            )
            return (out * out).sum()

        return jax.grad(f, argnums=(0, 1))

    g_pal = loss("pallas")(value, attn)
    g_ref = loss("xla")(value, attn)
    for a, b in zip(g_pal, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("backend", ["pallas", "pallas_sep"])
def test_sort_disabled_matches_xla(backend, monkeypatch):
    """SPOTTER_TPU_MSDA_SORT=0 (identity permutation, no q-row permutes) is a
    pure performance knob: results must match the sorted path bit-for-policy."""
    import spotter_tpu.ops.msda as M

    monkeypatch.setattr(M, "MSDA_SORT", False)
    value, loc, attn = _random_inputs(4)
    got = deformable_sampling(
        value, loc, attn, SHAPES, P, backend=backend, interpret=True
    )
    ref = deformable_sampling(value, loc, attn, SHAPES, P, backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_discrete_method_parity():
    """Discrete (nearest, border-clamped) path: XLA vs original formulation."""
    value, loc, attn = _random_inputs(2)
    got = deformable_sampling(value, loc, attn, SHAPES, P, method="discrete", backend="xla")
    pal = deformable_sampling(
        value, loc, attn, SHAPES, P, method="discrete", backend="pallas", interpret=True
    )
    pg = deformable_sampling(
        value, loc, attn, SHAPES, P, method="discrete",
        backend="pallas_gather", interpret=True,
    )
    ps = deformable_sampling(
        value, loc, attn, SHAPES, P, method="discrete",
        backend="pallas_sep", interpret=True,
    )
    # original discrete formulation from the module (pre-fusion)
    sampled = []
    start = 0
    for lvl, (h, w) in enumerate(SHAPES):
        v = value[:, start : start + h * w]
        start += h * w
        flat = v.transpose(0, 2, 1, 3).reshape(B * H, h * w, HD)
        g = loc[:, :, :, lvl * P : (lvl + 1) * P, :]
        g = g.transpose(0, 2, 1, 3, 4).reshape(B * H, Q, P, 2)
        coord = jnp.floor(g * jnp.asarray([w, h], jnp.float32) + 0.5).astype(jnp.int32)
        cx = jnp.clip(coord[..., 0], 0, w - 1)
        cy = jnp.clip(coord[..., 1], 0, h - 1)
        idx = (cy * w + cx).reshape(B * H, -1, 1)
        sampled.append(
            jnp.take_along_axis(flat, idx, axis=1).reshape(B * H, Q, P, HD)
        )
    sampled = jnp.concatenate(sampled, axis=2)
    aw = jnp.asarray(attn).transpose(0, 2, 1, 3).reshape(B * H, Q, LP, 1)
    ref = (sampled * aw).sum(axis=2)
    ref = ref.reshape(B, H, Q, HD).transpose(0, 2, 1, 3).reshape(B, Q, H * HD)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(pg), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(ps), np.asarray(ref), atol=1e-5)


def test_pallas_gather_gradients_match_xla():
    """custom_vjp backward == autodiff through the XLA path (train parity)."""
    value, loc, attn = _random_inputs(3)
    loc_t = loc.transpose(0, 2, 3, 1, 4)
    attn_t = jnp.asarray(attn).transpose(0, 2, 3, 1)
    idx, w = prepare_msda_gather(loc_t, attn_t, SHAPES, P)
    vt = value.transpose(0, 2, 3, 1)  # (B, H, HD, S)

    def loss_pallas(vt, w):
        return (
            pallas_deformable_sampling(vt, idx, w, LP, Q, True) ** 2
        ).sum()

    def loss_xla(vt, w):
        return (xla_deformable_sampling(vt, idx, w, LP, Q) ** 2).sum()

    gp_v, gp_w = jax.grad(loss_pallas, argnums=(0, 1))(vt, w)
    gx_v, gx_w = jax.grad(loss_xla, argnums=(0, 1))(vt, w)
    np.testing.assert_allclose(np.asarray(gp_v), np.asarray(gx_v), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gp_w), np.asarray(gx_w), atol=1e-4)


def test_onehot_gradients_match_xla():
    """One-hot kernel custom_vjp == autodiff through the sampling op."""
    value, loc, attn = _random_inputs(5)

    def loss(backend):
        def f(v, a):
            out = deformable_sampling(
                v, loc, a, SHAPES, P, backend=backend, interpret=True
            )
            return (out**2).sum()

        return f

    gp_v, gp_a = jax.grad(loss("pallas"), argnums=(0, 1))(value, jnp.asarray(attn))
    gx_v, gx_a = jax.grad(loss("xla"), argnums=(0, 1))(value, jnp.asarray(attn))
    np.testing.assert_allclose(np.asarray(gp_v), np.asarray(gx_v), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gp_a), np.asarray(gx_a), atol=1e-4)
    gs_v, gs_a = jax.grad(loss("pallas_sep"), argnums=(0, 1))(value, jnp.asarray(attn))
    np.testing.assert_allclose(np.asarray(gs_v), np.asarray(gx_v), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gs_a), np.asarray(gx_a), atol=1e-4)


@pytest.mark.tpu
@pytest.mark.parametrize("backend", ["pallas", "pallas_gather"])
def test_pallas_compiled_on_tpu(backend):
    """Mosaic-compiled kernels vs XLA on hardware.

    The one-hot kernel must work at any source size; the gather kernel is
    pinned at its single-vreg envelope ("Multiple source vregs along gather
    dimension" beyond 128 lanes) so a Mosaic upgrade lifting it is noticed.
    """
    if jax.default_backend() != "tpu":
        pytest.skip("requires TPU hardware")
    value, loc, attn = _random_inputs(4)
    got = jax.jit(
        lambda v, l, a: deformable_sampling(v, l, a, SHAPES, P, backend=backend)
    )(value, loc, attn)
    ref = deformable_sampling(value, loc, attn, SHAPES, P, backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_backend_policy(monkeypatch):
    monkeypatch.delenv(MSDA_ENV, raising=False)
    # auto: level-split one-hot kernel on TPU, XLA row-gathers on CPU/GPU
    expected = "pallas" if jax.default_backend() == "tpu" else "xla"
    assert msda_backend() == expected
    monkeypatch.setenv(MSDA_ENV, "pallas")
    assert msda_backend() == "pallas"
    assert msda_backend("xla") == "xla"
    assert msda_backend("pallas_gather") == "pallas_gather"
    monkeypatch.setenv(MSDA_ENV, "nope")
    with pytest.raises(ValueError):
        msda_backend()


@pytest.mark.parametrize("backend", ["pallas", "pallas_sep"])
def test_presorted_matches_xla(backend):
    """`presorted=True` (caller promises locality order — the decoder-level
    presort of rtdetr/deformable_detr) must be exact for ANY input order:
    hit tables come from the actual indices, so ordering is sparsity-only.
    Exercised with deliberately UNSORTED queries to pin the
    never-suppresses-a-hit property under a broken promise."""
    value, loc, attn = _random_inputs(3)
    got = deformable_sampling(
        value, loc, attn, SHAPES, P, backend=backend, interpret=True, presorted=True
    )
    ref = deformable_sampling(value, loc, attn, SHAPES, P, backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("method", ["default", "discrete"])
def test_kernel_prep_matches_xla(method, monkeypatch):
    """SPOTTER_TPU_MSDA_PREP=kernel (in-kernel corner decomposition +
    y-only hit table) must match the XLA gather reference exactly,
    including out-of-bounds corners and the discrete method."""
    monkeypatch.setattr(M, "MSDA_PREP", "kernel")
    value, loc, attn = _random_inputs(5)
    got = deformable_sampling(
        value, loc, attn, SHAPES, P, method=method, backend="pallas", interpret=True
    )
    monkeypatch.setattr(M, "MSDA_PREP", "xla")
    ref = deformable_sampling(value, loc, attn, SHAPES, P, method=method, backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_kernel_prep_gradients_match_xla(monkeypatch):
    """The loc-prep custom VJP (backward through the jnp corner reference)
    must agree with the XLA path's autodiff gradients for value, loc, attn."""
    value, loc, attn = _random_inputs(6)

    def loss(v, l, a, backend):
        out = deformable_sampling(
            v, l, a, SHAPES, P, backend=backend, interpret=(backend == "pallas")
        )
        return jnp.sum(out * jnp.cos(jnp.arange(out.size).reshape(out.shape)))

    monkeypatch.setattr(M, "MSDA_PREP", "kernel")
    g_kernel = jax.grad(loss, argnums=(0, 1, 2))(value, loc, attn, "pallas")
    monkeypatch.setattr(M, "MSDA_PREP", "xla")
    g_ref = jax.grad(loss, argnums=(0, 1, 2))(value, loc, attn, "xla")
    for gk, gr, name in zip(g_kernel, g_ref, ("value", "loc", "attn")):
        np.testing.assert_allclose(
            np.asarray(gk), np.asarray(gr), atol=2e-4, err_msg=name
        )


@pytest.mark.parametrize(
    "sg,nest", [(8, False), (0, True), (8, True)], ids=["sg8", "nest", "sg8+nest"]
)
def test_subgroup_and_nested_modes_match_xla(sg, nest, monkeypatch):
    """MSDA_SG (per-sublane-group hit bits) and MSDA_NEST (first-match
    corner select trees with sentinel indices) are exact rewrites of the
    merged one-hot kernel — including with out-of-bounds sample points,
    whose clamped corner indices are what the NEST sentinels exist for."""
    # Q_TILE=64 > Q=7: padded query rows carry zero weights through both modes.
    # References are computed BEFORE the monkeypatch: with SG/NEST active the
    # dispatch rejects every non-pallas backend (see the guard in
    # deformable_sampling) rather than silently ignoring the knobs.
    cases = []
    for method in ("default", "discrete"):
        value, loc, attn = _random_inputs(3)
        ref = deformable_sampling(
            value, loc, attn, SHAPES, P, method=method, backend="xla"
        )
        cases.append((method, value, loc, attn, ref))
    monkeypatch.setattr(M, "MSDA_SG", sg)
    monkeypatch.setattr(M, "MSDA_NEST", nest)
    for method, value, loc, attn, ref in cases:
        got = deformable_sampling(
            value, loc, attn, SHAPES, P, method=method, backend="pallas",
            interpret=True,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_nested_mode_gradients_match_xla(monkeypatch):
    """NEST gradient regression: the sentinel rewrite must stay KERNEL-
    facing only. If it leaked into the custom-VJP residuals, a valid
    corner with exactly-zero bilinear weight (sample point ON a grid
    line) would make the gather-backward read a clamped sentinel row and
    corrupt the location gradient through d_w (found by review, round 4:
    grad diff up to 10.0 before the fix)."""
    value, loc, attn = _random_inputs(5)
    # force several points exactly onto grid lines of the 8x8 level:
    # x*8 - 0.5 integral -> fx == 0 with both corners in-bounds
    loc = loc.at[:, :3, :, 0, 0].set(0.3125)
    loc = loc.at[:, :3, :, 0, 1].set(0.5625)

    def loss(bk, interp):
        def f(v, l, a):
            return jnp.sum(
                deformable_sampling(
                    v, l, a, SHAPES, P, backend=bk, interpret=interp
                )
                ** 2
            )

        return f

    # reference first: with NEST active the dispatch rejects backend="xla"
    g_ref = jax.grad(loss("xla", False), (0, 1, 2))(value, loc, attn)
    monkeypatch.setattr(M, "MSDA_NEST", True)
    g_nest = jax.grad(loss("pallas", True), (0, 1, 2))(value, loc, attn)
    for a, b in zip(g_nest, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_sg_nest_reject_per_call_backend_override(monkeypatch):
    """A per-call `backend=` override must not silently no-op SG/NEST
    (ADVICE r4: the import-time env guard alone misses call-site overrides,
    so an A/B harness could record a wrong conclusion)."""
    value, loc, attn = _random_inputs(9)
    for sg, nest in ((8, False), (0, True)):
        monkeypatch.setattr(M, "MSDA_SG", sg)
        monkeypatch.setattr(M, "MSDA_NEST", nest)
        for bk in ("xla", "pallas_sep", "pallas_gather"):
            with pytest.raises(ValueError, match="merged one-hot"):
                deformable_sampling(
                    value, loc, attn, SHAPES, P, backend=bk, interpret=True
                )
        # the merged one-hot path itself stays accepted
        deformable_sampling(
            value, loc, attn, SHAPES, P, backend="pallas", interpret=True
        )


def test_sg_nest_knob_validation():
    """Conflicting knob combinations must raise at import, not no-op."""
    import subprocess
    import sys

    for env in (
        {"SPOTTER_TPU_MSDA_SG": "8", "SPOTTER_TPU_MSDA": "xla"},
        {"SPOTTER_TPU_MSDA_NEST": "1", "SPOTTER_TPU_MSDA": "pallas_sep"},
        {"SPOTTER_TPU_MSDA_SG": "8", "SPOTTER_TPU_MSDA_PREP": "kernel"},
        {"SPOTTER_TPU_MSDA_NEST": "1", "SPOTTER_TPU_MSDA_PREP": "kernel"},
        {"SPOTTER_TPU_MSDA_SG": "12"},
        # ADVICE r5 #3: knobs + `auto` on a CPU host (auto -> xla) must fail
        # fast at import, not abort every forward at call time
        {"SPOTTER_TPU_MSDA_SG": "8"},
        {"SPOTTER_TPU_MSDA_NEST": "1"},
    ):
        proc = subprocess.run(
            [sys.executable, "-c", "import spotter_tpu.ops.msda"],
            env={**os.environ, "JAX_PLATFORMS": "cpu", **env},
            capture_output=True,
            text=True,
        )
        assert proc.returncode != 0, env
        assert "SPOTTER_TPU_MSDA" in proc.stderr, (env, proc.stderr[-500:])
