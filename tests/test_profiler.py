"""Profiling subsystem (SURVEY.md §5.1): trace capture, per-stage metrics,
and the /profile endpoint — all on the CPU test backend."""

import asyncio
import glob
import os
from io import BytesIO
from unittest.mock import AsyncMock

import httpx
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

os.environ["SPOTTER_TPU_TINY"] = "1"

import jax.numpy as jnp

from spotter_tpu.engine import profiler
from spotter_tpu.engine.metrics import Metrics


def test_capture_writes_trace(tmp_path):
    log_dir = str(tmp_path / "trace")
    # some device work for the trace window
    _ = jnp.ones((128, 128)) @ jnp.ones((128, 128))
    summary = profiler.capture(log_dir, duration_s=0.05)
    assert summary["log_dir"] == log_dir
    # jax writes plugins/profile/<ts>/*.xplane.pb under the log dir
    assert glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"), recursive=True)


def test_capture_rejects_bad_duration(tmp_path):
    # would otherwise wedge the process-wide profiler (start without stop)
    with pytest.raises(ValueError):
        profiler.capture(str(tmp_path / "bad"), duration_s=-1.0)
    with pytest.raises(ValueError):
        profiler.capture(str(tmp_path / "nan"), duration_s=float("nan"))
    # the profiler is still usable afterwards
    summary = profiler.capture(str(tmp_path / "ok"), duration_s=0.01)
    assert summary["log_dir"].endswith("ok")


def test_profiler_server_env(monkeypatch):
    monkeypatch.delenv(profiler.PROFILER_PORT_ENV, raising=False)
    assert profiler.maybe_start_profiler_server() is None


def test_stage_metrics_in_snapshot():
    m = Metrics()
    m.record_batch(4, 0.100, stages={"preprocess": 0.010, "device": 0.080})
    m.record_batch(4, 0.120, stages={"preprocess": 0.014, "device": 0.090})
    snap = m.snapshot()
    assert snap["stage_preprocess_ms_p50"] == pytest.approx(14.0)
    assert snap["stage_device_ms_p50"] == pytest.approx(90.0)
    assert snap["images_total"] == 8


def test_profile_endpoint(tmp_path, monkeypatch):
    monkeypatch.setenv("SPOTTER_TPU_PROFILE_DIR", str(tmp_path))
    from spotter_tpu.engine.batcher import MicroBatcher
    from spotter_tpu.engine.engine import InferenceEngine
    from spotter_tpu.models import build_detector
    from spotter_tpu.serving.detector import AmenitiesDetector
    from spotter_tpu.serving.standalone import make_app

    def _client_returning_image():
        img = Image.fromarray(np.full((32, 32, 3), 128, np.uint8))
        buf = BytesIO()
        img.save(buf, format="JPEG")
        resp = AsyncMock()
        resp.content = buf.getvalue()
        resp.raise_for_status = lambda: None
        client = AsyncMock(spec=httpx.AsyncClient)
        client.get.return_value = resp
        return client

    async def run():
        built = build_detector("PekingU/rtdetr_v2_r18vd")
        engine = InferenceEngine(built, threshold=0.0, batch_buckets=(1,))
        detector = AmenitiesDetector(
            engine, MicroBatcher(engine, max_delay_ms=1.0), _client_returning_image()
        )
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post("/profile", json={"duration_s": 0.05})
            assert resp.status == 200
            body = await resp.json()
            # server picks the dir (client paths rejected by design) under
            # SPOTTER_TPU_PROFILE_DIR
            assert body["log_dir"].startswith(str(tmp_path))
            assert glob.glob(
                os.path.join(body["log_dir"], "**", "*.xplane.pb"), recursive=True
            )
            # malformed bodies are 400s, like /detect
            assert (await client.post("/profile", json=[1])).status == 400
            assert (
                await client.post("/profile", json={"duration_s": "abc"})
            ).status == 400
            assert (
                await client.post("/profile", json={"duration_s": -1})
            ).status == 400
            # per-stage breakdown shows up in /metrics after one detect
            await client.post(
                "/detect", json={"image_urls": ["http://example.com/a.jpg"]}
            )
            snap = await (await client.get("/metrics")).json()
            assert "stage_device_ms_p50" in snap

    asyncio.run(run())


def test_capture_reports_overlapping_trace_ids(tmp_path, monkeypatch):
    """/profile <-> flight recorder join (ISSUE 10 satellite): a capture's
    summary carries the trace ids of requests whose window overlapped it,
    so an xprof trace can be lined up against /debug/traces."""
    import threading
    import time

    from spotter_tpu import obs

    monkeypatch.setenv(obs.TRACE_RING_ENV, "64")
    obs.reset_recorder()
    recorder = obs.get_recorder()
    try:
        summaries = []
        capture_started = threading.Event()

        def run_capture():
            capture_started.set()
            summaries.append(
                profiler.capture(str(tmp_path / "overlap"), duration_s=0.3)
            )

        t = threading.Thread(target=run_capture)
        t.start()
        capture_started.wait(2.0)
        # a request that starts AND finishes inside the capture window
        trace = obs.begin_trace(request_id="req-overlap", enabled=True)
        time.sleep(0.05)
        trace.finish()
        recorder.record(trace)
        obs.set_current_trace(None)
        t.join(timeout=10.0)
        (summary,) = summaries
        assert trace.trace_id in summary["overlapping_trace_ids"]
        # a trace recorded long before the window must NOT appear
        old = obs.begin_trace(request_id="req-old", enabled=True)
        old.started_at -= 3600.0
        old.finish()
        recorder.record(old)
        obs.set_current_trace(None)
        now = time.time()
        ids = recorder.trace_ids_between(now - 0.5, now + 0.5)
        assert old.trace_id not in ids
    finally:
        obs.reset_recorder()
