"""Training tests: exact matching vs scipy, loss sanity, sharded train step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

# the loss needs optax's jittable Hungarian solver; envs whose optax
# predates it skip this module (losses.py degrades to a lazy ImportError
# at call time, so collection elsewhere is unaffected)
pytest.importorskip(
    "optax.assignment", reason="optax lacks the assignment solver"
)

from spotter_tpu.models.rtdetr import RTDetrDetector
from spotter_tpu.models.zoo import tiny_rtdetr_config
from spotter_tpu.parallel import RTDETR_TP_RULES, data_sharding, make_mesh, shard_params
from spotter_tpu.train import (
    Targets,
    TrainBatch,
    create_train_state,
    detection_loss,
    hungarian_match,
    make_train_step,
)

# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def _random_targets(rng, b, t, num_labels):
    return Targets(
        labels=rng.integers(0, num_labels, (b, t)).astype(np.int32),
        boxes=np.clip(rng.random((b, t, 4)).astype(np.float32), 0.1, 0.9),
        valid=(rng.random((b, t)) < 0.7).astype(np.float32),
    )


def test_hungarian_match_is_exact_assignment():
    """Matched cost equals scipy's optimal assignment cost on the same matrix."""
    scipy_opt = pytest.importorskip("scipy.optimize")
    rng = np.random.default_rng(0)
    b, q, c, t = 3, 12, 7, 5
    logits = rng.standard_normal((b, q, c)).astype(np.float32)
    boxes = np.clip(rng.random((b, q, 4)).astype(np.float32), 0.05, 0.95)
    targets = _random_targets(rng, b, t, c)
    targets = Targets(targets.labels, targets.boxes, np.ones((b, t), np.float32))

    match = np.asarray(hungarian_match(jnp.asarray(logits), jnp.asarray(boxes), targets))
    assert match.shape == (b, t)

    from spotter_tpu.train.losses import _matching_cost

    for i in range(b):
        cost = np.asarray(
            _matching_cost(
                jnp.asarray(logits[i]), jnp.asarray(boxes[i]),
                Targets(targets.labels[i], targets.boxes[i], targets.valid[i]),
                2.0, 5.0, 2.0, 0.25, 2.0,
            )
        )
        rows, cols = scipy_opt.linear_sum_assignment(cost.T)
        scipy_cost = cost.T[rows, cols].sum()
        ours_cost = cost.T[np.arange(t), match[i]].sum()
        assert len(set(match[i].tolist())) == t  # one query per target
        assert ours_cost == pytest.approx(scipy_cost, rel=1e-5)


def test_detection_loss_finite_and_masked(debug_nans):
    rng = np.random.default_rng(1)
    cfg = tiny_rtdetr_config()
    module = RTDetrDetector(cfg)
    x = rng.standard_normal((2, 64, 64, 3)).astype(np.float32)
    params = module.init(jax.random.PRNGKey(0), x[:1])["params"]
    out = module.apply({"params": params}, x)
    targets = _random_targets(rng, 2, 4, cfg.num_labels)

    total, logged = detection_loss(out, Targets(*map(jnp.asarray, targets)))
    assert np.isfinite(float(total))
    assert float(logged["loss_bbox"]) >= 0 and float(logged["loss_giou"]) >= 0

    # all-padding targets: box losses vanish, loss stays finite
    empty = Targets(
        jnp.asarray(targets.labels),
        jnp.asarray(targets.boxes),
        jnp.zeros_like(jnp.asarray(targets.valid)),
    )
    total0, logged0 = detection_loss(out, empty)
    assert np.isfinite(float(total0))
    assert float(logged0["loss_bbox"]) == 0.0


def test_train_step_descends_on_fixed_batch(debug_nans):
    """A few steps on one batch must reduce the loss (overfit smoke test)."""
    rng = np.random.default_rng(2)
    cfg = tiny_rtdetr_config()
    module = RTDetrDetector(cfg)
    x = rng.standard_normal((2, 64, 64, 3)).astype(np.float32)
    params = module.init(jax.random.PRNGKey(0), x[:1])["params"]
    targets = _random_targets(rng, 2, 3, cfg.num_labels)
    batch = TrainBatch(jnp.asarray(x), Targets(*map(jnp.asarray, targets)))

    optimizer = optax.adamw(1e-3)
    state = create_train_state(params, optimizer)
    step = make_train_step(lambda p, v: module.apply({"params": p}, v), optimizer)

    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_train_step_sharded_matches_unsharded():
    """One dp*tp-sharded step == one single-device step (same numbers)."""
    rng = np.random.default_rng(3)
    cfg = tiny_rtdetr_config()
    module = RTDetrDetector(cfg)
    x = rng.standard_normal((4, 64, 64, 3)).astype(np.float32)
    params = module.init(jax.random.PRNGKey(0), x[:1])["params"]
    targets = _random_targets(rng, 4, 3, cfg.num_labels)

    optimizer = optax.adamw(1e-3)
    apply_fn = lambda p, v: module.apply({"params": p}, v)

    def run(params_in, put):
        batch = TrainBatch(
            put(jnp.asarray(x)), Targets(*(put(jnp.asarray(a)) for a in targets))
        )
        state = create_train_state(params_in, optimizer)
        step = make_train_step(apply_fn, optimizer, donate=False)
        state, metrics = step(state, batch)
        return float(metrics["loss"]), state

    loss_ref, state_ref = run(params, lambda a: a)

    mesh = make_mesh(dp=2, tp=2)
    data = data_sharding(mesh)
    loss_sh, state_sh = run(
        shard_params(params, mesh, RTDETR_TP_RULES), lambda a: jax.device_put(a, data)
    )
    assert loss_sh == pytest.approx(loss_ref, rel=1e-4)

    # updated params agree too (pick one TP-sharded leaf and one replicated)
    ref_leaf = np.asarray(state_ref.params["decoder_layer0"]["fc1"]["kernel"])
    sh_leaf = np.asarray(state_sh.params["decoder_layer0"]["fc1"]["kernel"])
    np.testing.assert_allclose(ref_leaf, sh_leaf, atol=1e-5)
