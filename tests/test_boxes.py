import jax.numpy as jnp
import numpy as np

from spotter_tpu.ops.boxes import (
    box_iou,
    center_to_corners,
    corners_to_center,
    generalized_box_iou,
    scale_boxes,
)


def test_center_corner_round_trip():
    boxes = jnp.array([[0.5, 0.5, 0.2, 0.4], [0.1, 0.9, 0.05, 0.1]])
    np.testing.assert_allclose(
        corners_to_center(center_to_corners(boxes)), boxes, atol=1e-6
    )


def test_center_to_corners_values():
    out = center_to_corners(jnp.array([[0.5, 0.5, 1.0, 0.5]]))
    np.testing.assert_allclose(out, [[0.0, 0.25, 1.0, 0.75]], atol=1e-6)


def test_scale_boxes_hw_convention():
    # target_sizes is [height, width] (serve.py:102)
    boxes = jnp.array([[[0.0, 0.0, 1.0, 1.0]]])
    out = scale_boxes(boxes, jnp.array([[480.0, 640.0]]))
    np.testing.assert_allclose(out, [[[0.0, 0.0, 640.0, 480.0]]], atol=1e-5)


def test_iou_identity_and_disjoint():
    a = jnp.array([[0.0, 0.0, 2.0, 2.0]])
    b = jnp.array([[0.0, 0.0, 2.0, 2.0], [3.0, 3.0, 4.0, 4.0], [1.0, 1.0, 3.0, 3.0]])
    iou, _ = box_iou(a, b)
    np.testing.assert_allclose(iou[0, 0], 1.0, atol=1e-6)
    np.testing.assert_allclose(iou[0, 1], 0.0, atol=1e-6)
    np.testing.assert_allclose(iou[0, 2], 1.0 / 7.0, atol=1e-6)


def test_giou_bounds_and_disjoint_penalty():
    a = jnp.array([[0.0, 0.0, 1.0, 1.0]])
    b = jnp.array([[2.0, 2.0, 3.0, 3.0]])
    giou = generalized_box_iou(a, b)
    assert giou[0, 0] < 0  # disjoint boxes are penalized below zero
    assert giou[0, 0] >= -1.0
    same = generalized_box_iou(a, a)
    np.testing.assert_allclose(same[0, 0], 1.0, atol=1e-6)
