"""Open-vocabulary workload suite (ISSUE 13): runtime `queries` through the
text-embedding cache, query-group batch isolation, the /detect wire contract
(tiny OWL-ViT on the virtual CPU mesh), and the closed-set 400."""

import asyncio
import os
from io import BytesIO
from unittest.mock import AsyncMock

import httpx
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer
from PIL import Image

os.environ["SPOTTER_TPU_TINY"] = "1"

from spotter_tpu.caching.keys import queries_key
from spotter_tpu.caching.text_cache import QuerySet, TextQueryResolver
from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.engine import InferenceEngine
from spotter_tpu.engine.metrics import Metrics
from spotter_tpu.engine.scheduler import QueueItem, Scheduler
from spotter_tpu.models import build_detector
from spotter_tpu.serving.detector import AmenitiesDetector, QueriesUnsupportedError
from spotter_tpu.serving.standalone import make_app


@pytest.fixture(scope="module")
def owl():
    built = build_detector("google/owlvit-base-patch32")
    engine = InferenceEngine(built, threshold=0.0, batch_buckets=(1, 2, 4))
    return built, engine


def _images(n, seed=5):
    rng = np.random.default_rng(seed)
    return [
        Image.fromarray(rng.integers(0, 255, (36, 36, 3), np.uint8))
        for _ in range(n)
    ]


def _stub_http_client():
    img = Image.fromarray(np.full((32, 32, 3), 96, np.uint8))
    buf = BytesIO()
    img.save(buf, format="JPEG")
    resp = AsyncMock()
    resp.content = buf.getvalue()
    resp.raise_for_status = lambda: None
    client = AsyncMock(spec=httpx.AsyncClient)
    client.get.return_value = resp
    return client


# ---------------------------------------------------------------------------
# text-embedding cache
# ---------------------------------------------------------------------------


def test_queries_key_is_order_insensitive_and_model_scoped():
    assert queries_key("m", ["dog", "couch"]) == queries_key("m", ["couch", "dog"])
    assert queries_key("m", ["dog"]) != queries_key("m2", ["dog"])
    assert queries_key("m", ["dog"]) != queries_key("m", ["cat"])


def test_resolver_caches_and_pads(owl):
    built, engine = owl
    metrics = Metrics()
    res = TextQueryResolver(built.model_name, built.text_encoder,
                            metrics=metrics, pad=8)
    qs = res.resolve(["couch", "dog", "palm tree"])
    assert qs.labels == ("couch", "dog", "palm tree")  # canonical sorted
    assert qs.embeds.shape[0] == 8 and qs.mask.tolist() == [1, 1, 1, 0, 0, 0, 0, 0]
    # repeated vocabulary (any order) is a hit on the SAME entry
    assert res.resolve(["dog", "palm tree", "couch"]) is qs
    snap = metrics.snapshot()
    assert snap["text_cache_hits_total"] == 1
    assert snap["text_cache_misses_total"] == 1
    assert snap["text_cache_miss_ms_p50"] > snap["text_cache_hit_ms_p50"]


def test_resolver_rejects_empty_and_bounds_entries(owl):
    built, _ = owl
    res = TextQueryResolver(built.model_name, built.text_encoder, max_entries=2)
    with pytest.raises(ValueError):
        res.resolve(["", "  "])
    res.resolve(["a"]); res.resolve(["b"]); res.resolve(["c"])
    assert res.stats()["entries"] == 2  # LRU-bounded


def test_text_encoder_is_deterministic(owl):
    built, _ = owl
    a = built.text_encoder(["couch", "dog"])
    b = built.text_encoder(["couch", "dog"])
    np.testing.assert_array_equal(a, b)
    norms = np.linalg.norm(a, axis=-1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-5)


# ---------------------------------------------------------------------------
# engine + scheduler + batcher
# ---------------------------------------------------------------------------


def test_engine_detect_with_qset_labels_from_queries(owl):
    built, engine = owl
    res = TextQueryResolver(built.model_name, built.text_encoder)
    qs = res.resolve(["couch", "dog"])
    dets = engine.detect(_images(3), qset=qs)
    assert len(dets) == 3
    labels = {d["label"] for ds in dets for d in ds}
    assert labels and labels <= {"couch", "dog"}
    # deterministic across calls (same program, same constants)
    assert dets == engine.detect(_images(3), qset=qs)


def test_engine_qset_padding_is_invisible(owl):
    """The padded query slots (mask 0) can never produce a detection: the
    same vocabulary padded to different widths detects identically."""
    built, engine = owl
    res8 = TextQueryResolver(built.model_name, built.text_encoder, pad=8)
    res4 = TextQueryResolver(built.model_name, built.text_encoder, pad=4)
    imgs = _images(2, seed=9)
    a = engine.detect(imgs, qset=res8.resolve(["couch", "dog", "tv"]))
    b = engine.detect(imgs, qset=res4.resolve(["couch", "dog", "tv"]))
    for da, db in zip(a, b):
        assert [d["label"] for d in da] == [d["label"] for d in db]
        np.testing.assert_allclose(
            np.asarray([d["box"] for d in da], np.float32),
            np.asarray([d["box"] for d in db], np.float32),
            atol=1e-4,
        )


def test_closed_set_engine_rejects_qset():
    built = build_detector("PekingU/rtdetr_v2_r18vd")
    engine = InferenceEngine(built, threshold=0.0, batch_buckets=(1,))
    qs = QuerySet(
        key="k", digest="d", labels=("x",),
        embeds=np.zeros((8, 4), np.float32), mask=np.zeros((8,), np.int32),
    )
    with pytest.raises(ValueError, match="closed-set"):
        engine.detect(_images(1), qset=qs)


def test_scheduler_never_mixes_query_groups():
    def item(group):
        qs = None
        if group is not None:
            qs = QuerySet(
                key=group, digest=group, labels=("x",),
                embeds=np.zeros((1, 2), np.float32),
                mask=np.ones((1,), np.int32),
            )
        fut = type("F", (), {"done": staticmethod(lambda: False)})()
        img = Image.new("RGB", (16, 16))
        return QueueItem(image=img, fut=fut, qset=qs, t_submit=0.0)

    sched = Scheduler(spec=None, ragged=False)
    pending = [item("a"), item("a"), item("b"), item(None), item("a")]
    plan = sched.plan(pending, target=8)
    assert [it.group for it in plan.items] == ["a", "a", "a"]
    # the other groups stay pending, in order, for the next plans
    assert [it.group for it in pending] == ["b", None]
    plan2 = sched.plan(pending, target=8)
    assert [it.group for it in plan2.items] == ["b"]
    plan3 = sched.plan(pending, target=8)
    assert [it.group for it in plan3.items] == [None]
    assert pending == []


def test_batcher_dispatches_each_query_group_separately(owl):
    built, engine = owl
    res = TextQueryResolver(built.model_name, built.text_encoder)
    qs_a = res.resolve(["couch"])
    qs_b = res.resolve(["dog", "tv"])
    batcher = MicroBatcher(engine, max_delay_ms=30.0)
    imgs = _images(4, seed=13)

    async def drive():
        tasks = [
            batcher.submit(imgs[0], qset=qs_a),
            batcher.submit(imgs[1], qset=qs_a),
            batcher.submit(imgs[2], qset=qs_b),
            batcher.submit(imgs[3], qset=qs_b),
        ]
        results = await asyncio.gather(*tasks)
        await batcher.stop()
        return results

    results = asyncio.run(drive())
    for r in results[:2]:
        assert {d["label"] for d in r} <= {"couch"}
    for r in results[2:]:
        assert {d["label"] for d in r} <= {"dog", "tv"}
    # group isolation: 4 submits over 2 vocabularies can never be 1 batch
    assert engine.metrics.snapshot()["batches_total"] >= 2


# ---------------------------------------------------------------------------
# /detect wire contract
# ---------------------------------------------------------------------------


def test_detect_endpoint_open_vocab_round_trip(owl):
    built, engine = owl
    detector = AmenitiesDetector(
        engine, MicroBatcher(engine, max_delay_ms=1.0), _stub_http_client()
    )

    async def run():
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post("/detect", json={
                "image_urls": ["http://example.com/room.jpg"],
                "queries": ["couch", "potted plant"],
            })
            assert resp.status == 200
            body = await resp.json()
            (img,) = body["images"]
            labels = {d["label"] for d in img["detections"]}
            assert labels and labels <= {"couch", "potted plant"}
            # the description is built from the request's own vocabulary
            assert any(q in body["amenities_description"]
                       for q in ("couch", "potted plant"))
            assert img["labeled_image_base64"]

            # /healthz advertises the open-vocab capability + resolved mesh
            health = await (await client.get("/healthz")).json()
            assert health["open_vocab"]["enabled"] is True
            assert health["tp"] == 1 and health["mesh"] is None

            # repeated vocabulary hits the text cache
            await client.post("/detect", json={
                "image_urls": ["http://example.com/room.jpg"],
                "queries": ["potted plant", "couch"],
            })
            snap = await (await client.get("/metrics")).json()
            assert snap["text_cache_hits_total"] >= 1
            assert snap["text_cache_misses_total"] >= 1

            # absent queries keeps the exact closed-set reference contract
            resp = await client.post("/detect", json={
                "image_urls": ["http://example.com/room.jpg"],
            })
            assert resp.status == 200
            body = await resp.json()
            assert set(body.keys()) == {"amenities_description", "images"}

    asyncio.run(run())


def test_detect_endpoint_queries_on_closed_set_model_400():
    built = build_detector("PekingU/rtdetr_v2_r18vd")
    engine = InferenceEngine(built, threshold=0.0, batch_buckets=(1,))
    detector = AmenitiesDetector(
        engine, MicroBatcher(engine, max_delay_ms=1.0), _stub_http_client()
    )

    async def run():
        with pytest.raises(QueriesUnsupportedError):
            await detector.detect({
                "image_urls": ["http://example.com/a.jpg"],
                "queries": ["couch"],
            })
        app = make_app(detector=detector)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post("/detect", json={
                "image_urls": ["http://example.com/a.jpg"],
                "queries": ["couch"],
            })
            assert resp.status == 400
            assert "closed-set" in await resp.text()
        health = detector.health()
        assert health["open_vocab"] == {"enabled": False}

    asyncio.run(run())


def test_result_cache_key_separates_vocabularies(owl):
    """Cache armed: the same image bytes under two vocabularies (or under
    the closed set) never share a result-cache entry."""
    from spotter_tpu.caching.result_cache import ResultCache

    built, engine = owl
    cache = ResultCache(max_bytes=1 << 20, metrics=engine.metrics)
    detector = AmenitiesDetector(
        engine, MicroBatcher(engine, max_delay_ms=1.0), _stub_http_client(),
        cache=cache,
    )

    async def run():
        p = {"image_urls": ["http://example.com/a.jpg"]}
        await detector.detect({**p, "queries": ["couch"]})
        await detector.detect({**p, "queries": ["dog"]})
        await detector.detect(dict(p))
        assert cache.stats()["entries"] == 3  # three distinct key spaces
        hits_before = engine.metrics.snapshot()["cache_hits_total"]
        await detector.detect({**p, "queries": ["couch"]})
        assert engine.metrics.snapshot()["cache_hits_total"] == hits_before + 1
        await detector.aclose()

    asyncio.run(run())
