"""Numerical parity: Flax ConditionalDetrDetector vs HF torch
ConditionalDetrForObjectDetection. Tiny random-init config, no network —
same guarantee pattern as the other families."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import ConditionalDetrConfig as HFConditionalDetrConfig
from transformers import ResNetConfig as HFResNetConfig
from transformers.models.conditional_detr.modeling_conditional_detr import (
    ConditionalDetrForObjectDetection,
)

from spotter_tpu.convert.conditional_detr_rules import conditional_detr_rules
from spotter_tpu.convert.torch_to_jax import convert_state_dict
from spotter_tpu.models.conditional_detr import ConditionalDetrDetector
from spotter_tpu.models.configs import ConditionalDetrConfig


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def _tiny_hf_config():
    backbone = HFResNetConfig(
        embedding_size=8,
        hidden_sizes=[8, 12, 16, 24],
        depths=[1, 1, 1, 1],
        layer_type="basic",
        out_features=["stage4"],
    )
    return HFConditionalDetrConfig(
        use_timm_backbone=False,
        use_pretrained_backbone=False,
        backbone=None,  # the class defaults to backbone="resnet50"
        backbone_config=backbone,
        d_model=32,
        encoder_layers=2,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        num_queries=9,
        num_labels=7,
    )


def test_registry_routes_conditional_before_plain_detr():
    """'microsoft/conditional-detr-resnet-50' contains the plain-DETR match
    substring 'detr-resnet' — the conditional family must win, which relies
    on registration order. Pin it so a reorder can't silently route the
    name to the wrong architecture."""
    import os

    os.environ.setdefault("SPOTTER_TPU_TINY", "1")
    from spotter_tpu.models import build_detector
    from spotter_tpu.models.conditional_detr import ConditionalDetrDetector
    from spotter_tpu.models.detr import DetrDetector

    built = build_detector("microsoft/conditional-detr-resnet-50")
    assert isinstance(built.module, ConditionalDetrDetector)
    assert built.postprocess == "sigmoid_topk" and built.needs_mask
    plain = build_detector("facebook/detr-resnet-50")
    assert isinstance(plain.module, DetrDetector)


def test_conditional_detr_parity():
    hf_cfg = _tiny_hf_config()
    torch.manual_seed(0)
    model = ConditionalDetrForObjectDetection(hf_cfg).eval()
    with torch.no_grad():
        for m in model.modules():
            if hasattr(m, "running_mean"):
                m.running_mean.uniform_(-0.2, 0.2)
                m.running_var.uniform_(0.8, 1.2)

    cfg = ConditionalDetrConfig.from_hf(hf_cfg)
    params = convert_state_dict(
        model.state_dict(), conditional_detr_rules(cfg), strict=True
    )

    rng = np.random.default_rng(1)
    x = rng.uniform(0, 1, size=(2, 3, 64, 96)).astype(np.float32)
    mask = np.zeros((2, 64, 96), dtype=np.int64)
    mask[0, :64, :80] = 1
    mask[1, :48, :96] = 1

    with torch.no_grad():
        tout = model(torch.from_numpy(x), pixel_mask=torch.from_numpy(mask))

    jout = ConditionalDetrDetector(cfg).apply(
        {"params": params},
        np.transpose(x, (0, 2, 3, 1)),
        mask.astype(np.float32),
    )

    np.testing.assert_allclose(
        np.asarray(jout["pred_boxes"]), tout.pred_boxes.numpy(), atol=2e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(jout["logits"]), tout.logits.numpy(), atol=5e-4, rtol=1e-3
    )
