"""Output-integrity plane tests (ISSUE 17): the shared detection-diff
comparator (edge-case fuzz), the content-deterministic stub engine, the
golden probe + on-device weights attestation, verified readiness with the
exit-86 path, hard quarantine at the pool, quorum sampling with
third-replica arbitration, the supervisor's full exit-code ladder in one
table, and the INTEGRITY chaos matrix."""

import asyncio
import os
import sys
import time
import types

import numpy as np
import pytest
from PIL import Image

from spotter_tpu.obs import compare
from spotter_tpu.serving import integrity, lifecycle
from spotter_tpu.testing import faults
from spotter_tpu.testing.stub_engine import (
    StubEngine,
    StubHttpClient,
    content_fingerprint,
)

# ---------------------------------------------------------------------------
# obs/compare.py — the shared comparator (satellite: extracted from rollout)

TV = {"label": "tv", "score": 0.90, "box": [2.0, 2.0, 20.0, 24.0]}
BED = {"label": "bed", "score": 0.70, "box": [5.0, 5.0, 30.0, 30.0]}


def test_compare_empty_detections():
    assert compare.detections_equivalent([], [])
    assert not compare.detections_equivalent([TV], [])
    assert not compare.detections_equivalent([], [TV])
    # empty image lists and count mismatches
    assert compare.images_equivalent([], [])
    assert not compare.images_equivalent([[TV]], [])
    assert compare.images_equivalent([[]], [[]])
    assert compare.diff_detections([], []) is None
    assert compare.diff_detections([TV], []) is not None


def test_compare_label_permutation_is_order_invariant():
    a = [dict(TV), dict(BED)]
    b = [dict(BED), dict(TV)]  # same set, different order
    assert compare.detections_equivalent(a, b)
    # a LABEL swap (same scores/boxes, different labels) is NOT equivalent
    swapped = [dict(TV, label="bed"), dict(BED, label="tv")]
    assert not compare.detections_equivalent(a, swapped)


def test_compare_near_threshold_score_flutter():
    """Scores fluttering around a rounding boundary must compare EQUAL
    under the tolerance matcher — 0.494 vs 0.496 round to different 2dp
    values, and one false diff here could start a quarantine countdown."""
    a = [dict(TV, score=0.494)]
    b = [dict(TV, score=0.496)]
    assert compare.detections_equivalent(a, b)  # |d| = .002 << tol .05
    # just inside vs just past the tolerance
    assert compare.detections_equivalent(
        [dict(TV, score=0.50)], [dict(TV, score=0.549)]
    )
    assert not compare.detections_equivalent(
        [dict(TV, score=0.50)], [dict(TV, score=0.56)]
    )


def test_compare_box_order_and_tolerance():
    a = [dict(TV, box=[2.0, 2.0, 20.0, 24.0])]
    assert compare.detections_equivalent(
        a, [dict(TV, box=[3.9, 0.1, 21.9, 22.1])]  # every coord within 2px
    )
    assert not compare.detections_equivalent(
        a, [dict(TV, box=[2.0, 2.0, 20.0, 27.0])]  # one coord 3px off
    )
    # a box-less detection only matches a box-less detection
    assert compare.detections_equivalent(
        [{"label": "tv", "score": 0.9}], [{"label": "tv", "score": 0.9}]
    )
    assert not compare.detections_equivalent(
        [{"label": "tv", "score": 0.9}], [dict(TV)]
    )


def test_compare_rollout_reexport_intact():
    """rollout.py re-exports the moved normalizer; the 2dp shadow-diff
    semantics must be byte-compatible with the pre-extraction local."""
    from spotter_tpu.serving.rollout import _norm_detections

    assert _norm_detections is compare.norm_detections
    assert compare.norm_detections(
        [{"detections": [dict(TV, score=0.904)]}]
    ) == compare.norm_detections([{"detections": [dict(TV, score=0.898)]}])


# ---------------------------------------------------------------------------
# stub engine determinism (satellite bugfix: input-independent detections
# made every diff-based test vacuous)


def _pil(fill: int):
    return Image.fromarray(np.full((8, 8, 3), fill % 256, np.uint8))


def test_stub_detections_are_function_of_input_content():
    eng_a, eng_b = StubEngine(), StubEngine()
    img1, img2 = _pil(10), _pil(200)
    # same input -> same output, across engine instances (honest replicas
    # with the same weights must agree)
    out_a = eng_a.detect([img1])[0]
    out_b = eng_b.detect([img1])[0]
    assert out_a == out_b
    # different input -> measurably different output (the regression: the
    # old stub answered identically for EVERY input)
    assert content_fingerprint(img1) != content_fingerprint(img2)
    assert eng_a.detect([img2])[0] != out_a
    # and repeatable
    assert eng_a.detect([img1])[0] == out_a


def test_stub_attest_catches_corrupt_weights():
    eng = StubEngine()
    assert eng.attest()["ok"]
    before = eng.detect([_pil(10)])[0]
    eng.corrupt_weights(1)
    report = eng.attest()
    assert not report["ok"] and report["mismatched"] == ["stub:0"]
    # the corruption perturbs outputs past the comparator tolerance — the
    # same signature a flipped real weight bit produces
    after = eng.detect([_pil(10)])[0]
    assert not compare.detections_equivalent(before, after)
    # corrupting one stub must not leak into others (deep-copy regression)
    assert StubEngine().attest()["ok"]


# ---------------------------------------------------------------------------
# faults.py seams


def test_faults_env_parses_integrity_keys(monkeypatch):
    monkeypatch.setenv(
        faults.FAULTS_ENV,
        "sdc=25,corrupt_weights=2,corrupt_compile_cache=1",
    )
    plan = faults.maybe_activate_from_env()
    try:
        assert plan.sdc == 25
        assert plan.corrupt_weights == 2
        assert plan.corrupt_compile_cache == 1
    finally:
        faults._active = None


def test_perturb_detections_exceeds_tolerance():
    dets = [dict(TV), dict(TV, score=0.2)]
    out = faults.perturb_detections(dets)
    assert not compare.detections_equivalent(dets, out)
    for d in out:
        assert 0.0 <= d["score"] <= 1.0


def test_corrupt_detections_bresenham_and_scope():
    with faults.inject(sdc=50, only_replica="r0"):
        fired = sum(
            faults.corrupt_detections([dict(TV)], "r0") != [dict(TV)]
            for _ in range(8)
        )
        assert fired == 4  # exact 50% share, no RNG
        # out-of-scope replica: never corrupted
        for _ in range(8):
            assert faults.corrupt_detections([dict(TV)], "r1") == [dict(TV)]
    # unarmed: passthrough
    assert faults.corrupt_detections([dict(TV)], "r0") == [dict(TV)]


def test_take_corrupt_weights_consumes_whole():
    with faults.inject(corrupt_weights=3):
        assert faults.take_corrupt_weights() == 3
        assert faults.take_corrupt_weights() == 0  # consumed whole
    assert faults.take_corrupt_weights() == 0


# ---------------------------------------------------------------------------
# golden probe + attestor + plane


def _stub_det():
    from spotter_tpu.engine.batcher import MicroBatcher
    from spotter_tpu.serving.detector import AmenitiesDetector

    eng = StubEngine()
    return AmenitiesDetector(
        eng, MicroBatcher(eng, max_delay_ms=1.0), StubHttpClient()
    )


def test_probe_image_deterministic():
    a, b = integrity.probe_image("stub"), integrity.probe_image("stub")
    assert a.tobytes() == b.tobytes()
    assert (
        integrity.probe_image("stub").tobytes()
        != integrity.probe_image("owlv2").tobytes()
    )


def test_golden_probe_pinned_stub_passes_and_catches_corruption():
    det = _stub_det()

    async def run():
        probe = integrity.GoldenProbe("stub")
        assert probe.reference is not None  # pinned in the registry
        assert await probe.run(det.batcher) is None
        # corrupt the live weights: the probe's answer moves past tolerance
        det.engine.corrupt_weights(1)
        reason = await probe.run(det.batcher)
        assert reason is not None and "tol" in reason
        snap = probe.snapshot()
        assert snap["probes_total"] == 2 and snap["failures_total"] == 1
        await det.batcher.stop()

    asyncio.run(run())


def test_golden_probe_self_pins_unknown_family():
    det = _stub_det()

    async def run():
        probe = integrity.GoldenProbe("some-unpinned-model")
        assert probe.reference is None
        assert await probe.run(det.batcher) is None  # first run self-pins
        assert probe.reference is not None
        assert await probe.run(det.batcher) is None  # and must keep matching
        await det.batcher.stop()

    asyncio.run(run())


def test_plane_verify_corrupt_compile_cache_attests_clean_probe_fails():
    """The miscompiled-restore shape: weights attest CLEAN (the cache
    poisoned the executable, not the params) — only the probe catches it."""
    det = _stub_det()

    async def run():
        exits = []
        plane = integrity.IntegrityPlane(
            det.engine, det.batcher, family="stub",
            probe_interval_s=0, attest_interval_s=0, exit_cb=exits.append,
        )
        with faults.inject(corrupt_compile_cache=1):
            ok = await plane.verify("warm-restore")
        assert not ok
        assert plane.attestor.failures_total == 0  # attest was clean
        assert plane.probe.failures_total == 1
        # the fault is consume-once: a re-verify (post cold restart) passes
        assert await plane.verify("cold-start")
        await det.batcher.stop()

    asyncio.run(run())


def test_plane_periodic_loop_exits_86_on_corruption():
    det = _stub_det()

    async def run():
        exits = []
        plane = integrity.IntegrityPlane(
            det.engine, det.batcher, family="stub",
            probe_interval_s=0.05, attest_interval_s=0.05,
            exit_cb=exits.append,
        )
        assert await plane.verify("cold-start")
        await plane.start()
        det.engine.corrupt_weights(1)  # silent corruption mid-serving
        for _ in range(100):
            if exits:
                break
            await asyncio.sleep(0.02)
        assert exits == [lifecycle.INTEGRITY_EXIT_CODE]
        await plane.aclose()
        await det.batcher.stop()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# on-device attestation over real jax arrays (CPU; shards across however
# many devices the platform exposes — CI runs this with 2 virtual devices)


def test_engine_attest_bit_exact_across_dtypes_and_shards():
    import jax

    from spotter_tpu.engine.engine import InferenceEngine

    params = {
        "w_f32": jax.numpy.arange(64, dtype=jax.numpy.float32) / 7.0,
        "w_i8": jax.numpy.array([-1, 0, 1, 127, -128], dtype=jax.numpy.int8),
        "w_bf16": jax.numpy.arange(32, dtype=jax.numpy.bfloat16) / 3.0,
    }
    host = {k: np.asarray(v) for k, v in params.items()}
    fake = types.SimpleNamespace(
        params=params, built=types.SimpleNamespace(params=host)
    )
    report = InferenceEngine.attest(fake)
    assert report["ok"], report
    assert report["checked"] >= 1
    assert report["observed"] == report["expected"]

    # a single flipped element on device is caught; host copy is pristine
    InferenceEngine.corrupt_weights(fake, 1)
    report = InferenceEngine.attest(fake)
    assert not report["ok"]
    assert report["mismatched"]

    # sharded placement: same checksums wherever the shards live
    devs = jax.devices()
    if len(devs) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.array(devs), ("dp",))
        arr = jax.device_put(
            jax.numpy.arange(len(devs) * 8, dtype=jax.numpy.float32),
            NamedSharding(mesh, PartitionSpec("dp")),
        )
        fake2 = types.SimpleNamespace(
            params={"w": arr},
            built=types.SimpleNamespace(params={"w": np.asarray(arr)}),
        )
        report = InferenceEngine.attest(fake2)
        assert report["ok"], report
        assert report["checked"] == len(devs)  # one checksum per device


# ---------------------------------------------------------------------------
# hard quarantine at the pool


def _pool(n=3):
    from spotter_tpu.serving.replica_pool import ReplicaPool

    return ReplicaPool(
        [f"http://10.0.0.{i}:80" for i in range(n)], health_interval_s=3600
    )


def test_pool_quarantine_zero_weight_and_refusals():
    pool = _pool(3)
    url = pool.replicas[0].url
    assert pool.quarantine(url, reason="test")
    assert not pool.replicas[0].available(time.monotonic())
    assert pool.quarantines_total == 1
    # idempotent refusal + unknown refusal, both counted
    assert not pool.quarantine(url)
    assert not pool.quarantine("http://nope:1")
    assert pool.quarantines_refused_total == 2
    snap = pool.snapshot()
    assert snap["pool_quarantines_total"] == 1
    assert snap["replicas"][0]["quarantined"]
    assert snap["replicas"][0]["quarantine_reason"] == "test"
    assert pool.unquarantine(url)
    assert pool.replicas[0].available(time.monotonic())


def test_pool_quarantine_never_takes_last_available_replica():
    pool = _pool(2)
    assert pool.quarantine(pool.replicas[0].url)
    # refusing the last one: wrong answers from ONE suspect replica beat a
    # full outage of the pool — and the refusal is loud, not silent
    assert not pool.quarantine(pool.replicas[1].url)
    assert pool.replicas[1].available(time.monotonic())


def test_pool_pick_other_excludes():
    pool = _pool(3)
    urls = [r.url for r in pool.replicas]
    w = pool.pick_other(exclude=(urls[0],))
    assert w in urls[1:]
    third = pool.pick_other(exclude=(urls[0], w))
    assert third in urls and third not in (urls[0], w)
    assert pool.pick_other(exclude=tuple(urls)) is None


# ---------------------------------------------------------------------------
# quorum sampler: Bresenham share + arbitration attribution


def test_quorum_take_exact_share():
    q = integrity.QuorumSampler(_pool(3), pct=25.0)
    assert sum(q.take() for _ in range(100)) == 25


class _ScriptedClient:
    """Answers /detect per url: callable -> body dict, None -> HTTP 500."""

    def __init__(self, answers):
        self.answers = answers

    async def post(self, url, json=None):
        base = url.rsplit("/detect", 1)[0]
        fn = self.answers[base]
        body = fn() if callable(fn) else fn

        class R:
            status_code = 500 if body is None else 200

            def json(self):
                return body

        return R()


def _quorum_fleet(n=3):
    pool = _pool(n)
    q = integrity.QuorumSampler(
        pool, pct=100.0, ewma_threshold=0.6, min_samples=2, alpha=0.5
    )
    return pool, q, [r.url for r in pool.replicas]


GOOD = {"images": [{"url": "u", "detections": [dict(TV)]}]}
BAD = {"images": [{"url": "u", "detections": [dict(TV, score=0.2)]}]}


def test_quorum_arbitration_charges_only_the_deviant():
    pool, q, urls = _quorum_fleet(3)
    corrupt = urls[0]
    client = _ScriptedClient(
        {corrupt: BAD, urls[1]: GOOD, urls[2]: GOOD}
    )

    async def run():
        import json as j

        # honest primary, corrupt witness possible: drive samples with the
        # corrupt replica as PRIMARY — the arbiter must side against it
        for _ in range(3):
            await q.run_one(client, {}, j.dumps(BAD), corrupt)
        assert q.disagreements_total == 3
        assert q.arbitrations_total == 3
        # only the deviant crossed the threshold
        assert not pool.replicas[0].available(time.monotonic())
        assert pool.replicas[1].available(time.monotonic()) and pool.replicas[2].available(time.monotonic())
        assert q.quarantines_total == 1
        # honest witnesses were charged NOTHING
        snap = q.snapshot()
        assert snap["ewma"][corrupt] >= 0.6
        for u in urls[1:]:
            assert snap["ewma"].get(u, 0.0) == 0.0

    asyncio.run(run())


def test_quorum_two_fleet_charges_both_but_honest_decays():
    """No third replica to arbitrate: both sides are charged on a
    disagreement — the EWMA's decay on agreeing samples is what keeps an
    honest replica under threshold over time."""
    pool, q, urls = _quorum_fleet(2)
    client = _ScriptedClient({urls[0]: GOOD, urls[1]: BAD})

    async def run():
        import json as j

        await q.run_one(client, {}, j.dumps(GOOD), urls[0])
        assert q.disagreements_total == 1 and q.arbitrations_total == 0
        snap = q.snapshot()
        assert snap["ewma"][urls[0]] == snap["ewma"][urls[1]] == 0.5

    asyncio.run(run())


def test_quorum_witness_error_not_charged():
    pool, q, urls = _quorum_fleet(3)
    client = _ScriptedClient({u: None for u in urls})  # every witness 500s

    async def run():
        import json as j

        for _ in range(5):
            await q.run_one(client, {}, j.dumps(GOOD), urls[0])
        assert q.errors_total == 5
        assert q.compared_total == 0 and q.disagreements_total == 0
        assert q.snapshot()["ewma"] == {}  # transport failure charges no one
        for r in pool.replicas:
            assert r.available(time.monotonic())

    asyncio.run(run())


# ---------------------------------------------------------------------------
# verified readiness through the real standalone bring-up (stub engine)


def test_bringup_verifies_then_ready(monkeypatch):
    from aiohttp.test_utils import TestClient, TestServer

    from spotter_tpu.serving.standalone import make_app

    monkeypatch.setenv("SPOTTER_TPU_STUB_ENGINE", "1")

    async def run():
        exits = []
        app = make_app(
            model_name=None, warmup=False,
            bringup_exit_cb=exits.append, integrity_exit_cb=exits.append,
        )
        async with TestClient(TestServer(app)) as client:
            for _ in range(300):
                r = await client.get("/startupz")
                if r.status == 200:
                    break
                await asyncio.sleep(0.01)
            assert r.status == 200
            snap = await (await client.get("/metrics")).json()
            integ = snap["integrity"]
            assert integ["verifications_total"] == 1
            assert integ["verification_failures_total"] == 0
            assert integ["probe"]["pinned"]
            assert not exits
            await app["detector"].batcher.stop()

    asyncio.run(run())


def test_bringup_corrupt_weights_exits_86_before_traffic(monkeypatch):
    from aiohttp.test_utils import TestClient, TestServer

    from spotter_tpu.serving.standalone import make_app

    monkeypatch.setenv("SPOTTER_TPU_STUB_ENGINE", "1")
    monkeypatch.setenv(faults.FAULTS_ENV, "corrupt_weights=1")
    faults.maybe_activate_from_env()

    async def run():
        exits = []
        app = make_app(
            model_name=None, warmup=False,
            bringup_exit_cb=exits.append, integrity_exit_cb=exits.append,
        )
        async with TestClient(TestServer(app)) as client:
            for _ in range(300):
                if exits:
                    break
                await asyncio.sleep(0.01)
            assert exits == [lifecycle.INTEGRITY_EXIT_CODE]
            r = await client.get("/startupz")
            body = await r.json()
            # never reached ready: the corruption was caught BEFORE traffic
            assert r.status == 503
            assert "checksum mismatch" in body["error"]

    try:
        asyncio.run(run())
    finally:
        faults._active = None


def test_integrity_disabled_skips_verification(monkeypatch):
    from aiohttp.test_utils import TestClient, TestServer

    from spotter_tpu.serving.standalone import make_app

    monkeypatch.setenv("SPOTTER_TPU_STUB_ENGINE", "1")
    monkeypatch.setenv(integrity.INTEGRITY_ENV, "0")

    async def run():
        app = make_app(model_name=None, warmup=False)
        async with TestClient(TestServer(app)) as client:
            for _ in range(300):
                r = await client.get("/startupz")
                if r.status == 200:
                    break
                await asyncio.sleep(0.01)
            assert r.status == 200
            snap = await (await client.get("/metrics")).json()
            assert "integrity" not in snap
            await app["detector"].batcher.stop()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# degraded-rebuild re-verification (the batcher-side gate)


class _DegradableEngine:
    def __init__(self):
        from spotter_tpu.engine.metrics import Metrics

        self.metrics = Metrics()
        self.batch_buckets = (1, 2, 4)
        self.generation = 0
        self.dp = 2

    def can_degrade(self):
        return True

    def probe_shards(self):
        return [0]

    def rebuild_degraded(self, alive):
        self.generation += 1
        self.dp = 1
        return 1

    def detect(self, images):
        return [[dict(TV)] for _ in images]


def test_rebuild_degraded_runs_integrity_recheck():
    from spotter_tpu.engine.batcher import MicroBatcher

    async def run():
        eng = _DegradableEngine()
        batcher = MicroBatcher(eng, max_delay_ms=1.0)
        tracker = lifecycle.StartupTracker()
        tracker.mark(lifecycle.WARMING)
        tracker.mark_ready(eng.metrics)
        batcher.attach_lifecycle(tracker)
        calls = []

        def recheck(source):
            calls.append((source, tracker.state))
            return True

        batcher.integrity_recheck_cb = recheck
        await batcher.start()
        assert await batcher._rebuild_degraded(0)
        # the recheck ran, in the VERIFYING state, before READY returned
        assert calls == [("degraded-rebuild", lifecycle.VERIFYING)]
        assert tracker.state == lifecycle.READY
        await batcher.stop()

    asyncio.run(run())


def test_rebuild_degraded_failed_recheck_blocks_ready_no_fatal_cascade():
    from spotter_tpu.engine.batcher import MicroBatcher

    async def run():
        eng = _DegradableEngine()
        batcher = MicroBatcher(eng, max_delay_ms=1.0)
        tracker = lifecycle.StartupTracker()
        tracker.mark(lifecycle.WARMING)
        tracker.mark_ready(eng.metrics)
        batcher.attach_lifecycle(tracker)
        batcher.integrity_recheck_cb = lambda source: False
        await batcher.start()
        # True = "handled": the recheck callback owns the exit-86 path and
        # the rebuild must NOT cascade into the fatal(85) exit underneath
        assert await batcher._rebuild_degraded(0)
        assert tracker.state == lifecycle.VERIFYING  # never back to ready
        await batcher.stop()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# the supervisor exit-code ladder, pinned in one table


def _ladder_child(counter_path: str, cache_dir: str, code: int) -> list[str]:
    """A child that exits `code` while the counter is positive, then 0 —
    and recreates the compile-cache dir each run, like a real bring-up."""
    script = (
        "import os,sys\n"
        f"p = {counter_path!r}\n"
        "n = int(open(p).read())\n"
        "open(p, 'w').write(str(n - 1))\n"
        f"os.makedirs({cache_dir!r}, exist_ok=True)\n"
        f"sys.exit({code} if n > 0 else 0)\n"
    )
    return [sys.executable, "-c", script]


# (code, failures, expected_return, expected_restarts, quarantined_dirs)
LADDER = [
    # clean stop: no restart at all
    (0, 0, 0, 0, 0),
    # bring-up failure (82) is a plain crash: backoff, then the crash-loop
    # circuit trips at the limit and the supervisor gives up with 84
    (lifecycle.BRINGUP_FAILED_EXIT_CODE, 99, 84, 3, 0),
    # drained preemption (83): immediate warm restarts, cache untouched
    (lifecycle.PREEMPTED_EXIT_CODE, 2, 0, 2, 0),
    # fatal engine (85): immediate warm restarts, cache untouched
    (85, 2, 0, 2, 0),
    # integrity (86): cold restarts, compile cache quarantined EVERY time
    (lifecycle.INTEGRITY_EXIT_CODE, 2, 0, 2, 2),
]


@pytest.mark.parametrize(
    "code,failures,want_return,want_restarts,want_quarantined",
    LADDER,
    ids=[f"exit-{row[0]}" for row in LADDER],
)
def test_supervisor_exit_code_ladder(
    tmp_path, monkeypatch, code, failures, want_return, want_restarts,
    want_quarantined,
):
    from spotter_tpu.serving.supervisor import Supervisor

    cache_dir = tmp_path / "compile-cache"
    monkeypatch.setenv(lifecycle.COMPILE_CACHE_ENV, str(cache_dir))
    counter = tmp_path / "count"
    counter.write_text(str(failures))
    sup = Supervisor(
        _ladder_child(str(counter), str(cache_dir), code),
        backoff_base_s=0.01,
        backoff_max_s=0.02,
        min_uptime_s=1.0,  # every exit counts as "fast"
        crash_loop_limit=3,
        preempt_fast_limit=3,
        jitter=False,
    )
    assert sup.run() == want_return
    assert sup.restarts_total == want_restarts
    quarantined = sorted(
        p.name for p in tmp_path.glob("compile-cache.quarantined.*")
    )
    assert len(quarantined) == want_quarantined
    if want_quarantined:
        # deterministic, collision-free naming preserved for forensics
        assert quarantined == [
            f"compile-cache.quarantined.{i}"
            for i in range(want_quarantined)
        ]


def test_exit_codes_are_distinct():
    """Every ladder rung is a distinct code — a collision would silently
    merge two restart policies."""
    from spotter_tpu.engine.errors import FATAL_ENGINE_EXIT_CODE
    from spotter_tpu.serving.supervisor import CRASH_LOOP_EXIT_CODE

    codes = [
        lifecycle.BRINGUP_FAILED_EXIT_CODE,
        lifecycle.PREEMPTED_EXIT_CODE,
        CRASH_LOOP_EXIT_CODE,
        FATAL_ENGINE_EXIT_CODE,
        lifecycle.INTEGRITY_EXIT_CODE,
    ]
    assert codes == [82, 83, 84, 85, 86]
    assert len(set(codes)) == len(codes)


# ---------------------------------------------------------------------------
# the integrity chaos matrix


@pytest.mark.parametrize(
    "idx", range(4), ids=[sc.name for sc in __import__(
        "spotter_tpu.testing.chaos_matrix", fromlist=["INTEGRITY_MATRIX"]
    ).INTEGRITY_MATRIX],
)
def test_integrity_chaos_matrix(idx):
    from spotter_tpu.testing.chaos_matrix import (
        INTEGRITY_MATRIX,
        run_integrity_scenario,
    )

    sc = INTEGRITY_MATRIX[idx]
    report = asyncio.run(run_integrity_scenario(sc))
    assert report["ok"], {
        "name": report["name"],
        "checks": report["checks"],
        "wrong_answers": report["wrong_answers"],
        "quarantines": report["quarantines"],
        "exits": report["exits"],
        "quorum": report["quorum"],
    }
