"""Tensor-parallel serving suite (ISSUE 13): dp×tp composition on the
virtual 8-device CPU mesh — regex rule machinery, sharding report, mesh/knob
validation, tp parity for tiny RT-DETR + tiny OWL-ViT, ragged scheduling
over a tp group, per-device HBM presence, and the can_degrade pin."""

import asyncio
import logging
import os

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from PIL import Image

os.environ["SPOTTER_TPU_TINY"] = "1"

from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.engine import InferenceEngine
from spotter_tpu.engine.scheduler import Scheduler
from spotter_tpu.models import build_detector
from spotter_tpu.models.registry import family_for
from spotter_tpu.parallel import (
    OWLVIT_TP_RULES,
    RTDETR_TP_RULES,
    check_rules_cover,
    format_sharding_report,
    make_mesh,
    match_partition_rules,
    sharding_report,
    unmatched_rules,
)
from spotter_tpu.serving import app as serving_app


# ---------------------------------------------------------------------------
# rule machinery
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_owlvit():
    return build_detector("google/owlvit-base-patch32")


@pytest.fixture(scope="module")
def tiny_rtdetr():
    return build_detector("PekingU/rtdetr_v2_r18vd")


def test_registry_carries_per_family_tp_rules():
    assert family_for("PekingU/rtdetr_v2_r101vd").tp_rules == tuple(
        RTDETR_TP_RULES
    )
    assert family_for("google/owlv2-base-patch16").tp_rules == tuple(
        OWLVIT_TP_RULES
    )
    # every registered family ships a rule set — no family is tp-dead
    for name in ("hustvl/yolos-base", "facebook/detr-resnet-50",
                 "facebook/deformable-detr"):
        assert family_for(name).tp_rules, name


def test_match_partition_rules_covers_both_owl_towers(tiny_owlvit):
    specs = match_partition_rules(OWLVIT_TP_RULES, tiny_owlvit.params)
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in kp): s
        for kp, s in jax.tree_util.tree_flatten_with_path(specs)[0]
    }
    assert flat["vision/layer0/self_attn/q_proj/kernel"] == P(None, "tp")
    assert flat["vision/layer0/fc2/kernel"] == P("tp", None)
    assert flat["text/layer0/self_attn/out_proj/kernel"] == P("tp", None)
    assert flat["text/layer1/fc1/kernel"] == P(None, "tp")
    # embeddings and heads replicate
    assert flat["text/token_embedding"] == P()
    assert flat["vision/patch_embedding/kernel"] == P()


def test_scalar_leaves_never_partition():
    params = {"fc1": {"kernel": np.zeros((4, 8)), "scale": np.zeros(())}}
    rules = ((r".*", P(None, "tp")),)
    specs = match_partition_rules(rules, params)
    assert specs["fc1"]["scale"] == P()
    assert specs["fc1"]["kernel"] == P(None, "tp")


def test_dead_rules_fail_loud(tiny_owlvit):
    dead_rule = (r".*/renamed_projection/kernel$", P(None, "tp"))
    rules = tuple(OWLVIT_TP_RULES) + (dead_rule,)
    assert unmatched_rules(tiny_owlvit.params, rules) == [dead_rule[0]]
    with pytest.raises(ValueError, match="renamed_projection"):
        check_rules_cover(tiny_owlvit.params, rules, family="owlvit")
    # the live set is clean
    check_rules_cover(tiny_owlvit.params, OWLVIT_TP_RULES, family="owlvit")


def test_engine_fails_loud_on_dead_rule_at_tp2(tiny_owlvit):
    rules = tuple(OWLVIT_TP_RULES) + ((r".*/ghost/kernel$", P(None, "tp")),)
    with pytest.raises(ValueError, match="ghost"):
        InferenceEngine(
            tiny_owlvit, batch_buckets=(2,),
            mesh=make_mesh(dp=1, tp=2, devices=jax.devices()[:2]),
            tp_rules=rules,
        )


def test_sharding_report_tiny_owlvit(tiny_owlvit):
    mesh = make_mesh(dp=2, tp=2)
    report = sharding_report(tiny_owlvit.params, mesh, OWLVIT_TP_RULES)
    assert report["unmatched_rules"] == []
    assert report["sharded_params"] > 0
    assert report["per_device_bytes"] < report["replicated_bytes"]
    sharded = [r for r in report["rows"] if r["sharded"]]
    assert any("self_attn/q_proj/kernel" in r["path"] for r in sharded)
    assert any("fc2/kernel" in r["path"] for r in sharded)
    # the dump renders with totals and the ratio line
    text = format_sharding_report(report, max_rows=5)
    assert "B/device" in text and "more params" in text


def test_sharding_report_vitl_class_backbone_splits():
    """The acceptance quantity on a ViT-L-class tree (via eval_shape — no
    init paid): per-device param bytes ≤ ~60% of replicated at tp=2, and
    every attention/MLP weight actually split."""
    from spotter_tpu.models.configs import (
        OwlViTConfig,
        OwlViTTextConfig,
        OwlViTVisionConfig,
    )
    from spotter_tpu.models.owlvit import OwlViTDetector

    cfg = OwlViTConfig(
        text=OwlViTTextConfig(),
        vision=OwlViTVisionConfig(
            hidden_size=1024, intermediate_size=4096, num_hidden_layers=24,
            num_attention_heads=16, image_size=224, patch_size=14,
        ),
        projection_dim=512,
    )
    module = OwlViTDetector(cfg)
    shapes = jax.eval_shape(
        lambda: module.init(
            jax.random.PRNGKey(0),
            np.zeros((1, 224, 224, 3), np.float32),
            np.zeros((4, 16), np.int32),
            np.ones((4, 16), np.int32),
            method=OwlViTDetector.detect_with_text,
        )
    )["params"]
    rep2 = sharding_report(shapes, make_mesh(dp=4, tp=2), OWLVIT_TP_RULES)
    assert rep2["per_device_ratio"] <= 0.60
    assert rep2["fallback_params"] == 0 and rep2["unmatched_rules"] == []
    rep4 = sharding_report(shapes, make_mesh(dp=2, tp=4), OWLVIT_TP_RULES)
    assert rep4["per_device_ratio"] < rep2["per_device_ratio"]


def test_indivisible_leaves_fall_back_replicated_and_are_flagged():
    params = {"blk": {"fc1": {"kernel": np.zeros((6, 10), np.float32)}}}
    mesh = make_mesh(dp=2, tp=4)
    report = sharding_report(params, mesh, RTDETR_TP_RULES)
    (row,) = [r for r in report["rows"] if r["path"].endswith("fc1/kernel")]
    assert row["fallback"] and not row["sharded"]  # 10 % 4 != 0
    assert report["per_device_bytes"] == report["replicated_bytes"]


# ---------------------------------------------------------------------------
# mesh / knob validation (satellite 1)
# ---------------------------------------------------------------------------


def test_make_mesh_errors_name_the_knob():
    with pytest.raises(ValueError, match="SPOTTER_TPU_MESH"):
        make_mesh(dp=8, tp=2, source="SPOTTER_TPU_MESH")
    with pytest.raises(ValueError, match="not divisible by tp"):
        make_mesh(tp=3, source="SPOTTER_TPU_SERVE_TP")
    with pytest.raises(ValueError, match="tp must be positive"):
        make_mesh(tp=0)


def test_serve_tp_env_parsing(monkeypatch):
    monkeypatch.delenv(serving_app.SERVE_TP_ENV, raising=False)
    assert serving_app.serve_tp_from_env() == 1
    monkeypatch.setenv(serving_app.SERVE_TP_ENV, "4")
    assert serving_app.serve_tp_from_env() == 4
    monkeypatch.setenv(serving_app.SERVE_TP_ENV, "two")
    with pytest.raises(ValueError, match="SPOTTER_TPU_SERVE_TP"):
        serving_app.serve_tp_from_env()


def test_bucket_dp_divisibility_rejected_up_front(monkeypatch):
    monkeypatch.setenv("SPOTTER_TPU_TINY", "1")
    monkeypatch.setenv("SPOTTER_TPU_BATCH_BUCKETS", "3,5")
    monkeypatch.setenv("SPOTTER_TPU_MESH", "dp=2")
    with pytest.raises(ValueError) as err:
        serving_app.build_detector_app("PekingU/rtdetr_v2_r18vd")
    # the message names both knobs so the operator knows what to fix
    assert "SPOTTER_TPU_BATCH_BUCKETS" in str(err.value)
    assert "dp=2" in str(err.value)


def test_oversized_mesh_spec_rejected_with_knob(monkeypatch):
    monkeypatch.setenv("SPOTTER_TPU_TINY", "1")
    monkeypatch.setenv("SPOTTER_TPU_MESH", "dp=8,tp=2")  # needs 16 devices
    with pytest.raises(ValueError, match="SPOTTER_TPU_MESH"):
        serving_app.build_detector_app("PekingU/rtdetr_v2_r18vd")


def test_mesh_wins_warning_and_healthz_surfaces_resolved_mesh(
    monkeypatch, caplog
):
    """Satellite 2: MESH + SERVE_DP/TP set together logs ONE explicit
    'MESH wins' warning, and the detector's health block carries the
    resolved mesh + its source."""
    monkeypatch.setenv("SPOTTER_TPU_TINY", "1")
    monkeypatch.setenv("SPOTTER_TPU_MESH", "dp=2")
    monkeypatch.setenv("SPOTTER_TPU_SERVE_DP", "4")
    monkeypatch.setenv("SPOTTER_TPU_SERVE_TP", "2")
    with caplog.at_level(logging.WARNING, logger="spotter_tpu.serving.app"):
        det = serving_app.build_detector_app("PekingU/rtdetr_v2_r18vd")
    wins = [r for r in caplog.records if "wins over" in r.getMessage()]
    assert len(wins) == 1
    assert "SPOTTER_TPU_SERVE_DP" in wins[0].getMessage()
    assert det.engine.dp == 2 and det.engine.tp == 1  # MESH won
    health = det.health()
    assert health["mesh"] == {
        "dp": 2, "tp": 1, "source": "SPOTTER_TPU_MESH",
    }
    assert health["tp"] == 1


def test_serve_dp_tp_compose_and_scale_buckets_by_dp_only(monkeypatch):
    monkeypatch.setenv("SPOTTER_TPU_TINY", "1")
    monkeypatch.delenv("SPOTTER_TPU_MESH", raising=False)
    monkeypatch.setenv("SPOTTER_TPU_SERVE_DP", "2")
    monkeypatch.setenv("SPOTTER_TPU_SERVE_TP", "2")
    det = serving_app.build_detector_app(
        "PekingU/rtdetr_v2_r18vd", batch_buckets=(1, 2)
    )
    eng = det.engine
    assert eng.dp == 2 and eng.tp == 2
    # ladder scaled by dp only: (1,2) -> (2,4); tp never multiplies it
    assert eng.batch_buckets == (2, 4)
    health = det.health()
    assert health["mesh"]["source"] == "SPOTTER_TPU_SERVE_DP x SPOTTER_TPU_SERVE_TP"
    assert health["tp"] == 2
    assert eng.can_degrade() is False  # tp>1: params are split, no shrink


# ---------------------------------------------------------------------------
# tp parity + serving composition (the dp×tp suite, satellite 3)
# ---------------------------------------------------------------------------


def _images(n, seed=7, hw=(40, 40)):
    rng = np.random.default_rng(seed)
    return [
        Image.fromarray(rng.integers(0, 255, (*hw, 3), np.uint8))
        for _ in range(n)
    ]


def _assert_parity(ref, out, atol=1e-3):
    assert len(ref) == len(out)
    for da, db in zip(ref, out):
        assert [d["label"] for d in da] == [d["label"] for d in db]
        if da:
            np.testing.assert_allclose(
                np.asarray([d["score"] for d in da], np.float32),
                np.asarray([d["score"] for d in db], np.float32),
                atol=1e-3,
            )
            np.testing.assert_allclose(
                np.asarray([d["box"] for d in da], np.float32),
                np.asarray([d["box"] for d in db], np.float32),
                atol=atol,
            )


def test_tp2_and_tp4_parity_tiny_owlvit(tiny_owlvit):
    imgs = _images(4)
    single = InferenceEngine(tiny_owlvit, threshold=0.0, batch_buckets=(4,))
    ref = single.detect(imgs)
    rules = family_for("owlvit").tp_rules
    for dp, tp in ((2, 2), (1, 4)):
        eng = InferenceEngine(
            tiny_owlvit, threshold=0.0, batch_buckets=(4,),
            mesh=make_mesh(dp=dp, tp=tp), tp_rules=rules,
        )
        _assert_parity(ref, eng.detect(imgs))
        assert eng.tp == tp and not eng.can_degrade()


def test_tp2_parity_tiny_rtdetr(tiny_rtdetr):
    imgs = _images(4, seed=3, hw=(64, 64))
    single = InferenceEngine(tiny_rtdetr, threshold=0.0, batch_buckets=(4,))
    ref = single.detect(imgs)
    eng = InferenceEngine(
        tiny_rtdetr, threshold=0.0, batch_buckets=(4,),
        mesh=make_mesh(dp=2, tp=2), tp_rules=family_for("rtdetr").tp_rules,
    )
    _assert_parity(ref, eng.detect(imgs), atol=1e-2)


def test_hbm_per_device_present_for_every_mesh_device(tiny_rtdetr):
    eng = InferenceEngine(
        tiny_rtdetr, threshold=0.0, batch_buckets=(4,),
        mesh=make_mesh(dp=2, tp=2), tp_rules=family_for("rtdetr").tp_rules,
    )
    hbm = eng.metrics.snapshot()["hbm_per_device"]
    mesh_ids = {str(d.id) for d in eng.devices()}
    assert mesh_ids <= set(hbm)
    assert len(mesh_ids) == 4


def test_ragged_scheduler_over_tp_group(tiny_rtdetr):
    """SPOTTER_TPU_RAGGED=1 semantics compose with a dp×tp mesh: the
    slack-ordered scheduler feeds the tp engine through the batcher and
    detections match the single-chip FIFO reference."""
    imgs = _images(4, seed=11, hw=(64, 64))
    single = InferenceEngine(tiny_rtdetr, threshold=0.0, batch_buckets=(4,))
    ref = single.detect(imgs)
    eng = InferenceEngine(
        tiny_rtdetr, threshold=0.0, batch_buckets=(4,),
        mesh=make_mesh(dp=2, tp=2), tp_rules=family_for("rtdetr").tp_rules,
    )
    sched = Scheduler(spec=tiny_rtdetr.preprocess_spec, ragged=True)
    batcher = MicroBatcher(eng, max_delay_ms=50.0, scheduler=sched)

    async def drive():
        results = await asyncio.gather(*(batcher.submit(im) for im in imgs))
        await batcher.stop()
        return results

    out = asyncio.run(drive())
    _assert_parity(ref, out, atol=1e-2)
    snap = eng.metrics.snapshot()
    assert snap["batches_total"] >= 1
    assert snap["aggregate_bucket"] == 4
