"""Precision policy: env/platform selection and bf16-vs-fp32 agreement.

The serving stack runs bf16 activations on TPU (MXU-native) with fp32 box
arithmetic in the heads; these tests pin the policy logic and check that a
bf16 forward stays close to the fp32 reference (the on-TPU analog of the
reference's ±1 px golden-box contract, test_serve.py:296-300).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spotter_tpu.models.rtdetr import RTDetrDetector
from spotter_tpu.models.zoo import tiny_rtdetr_config
from spotter_tpu.utils.precision import DTYPE_ENV, compute_dtype


def test_compute_dtype_env_override(monkeypatch):
    monkeypatch.setenv(DTYPE_ENV, "bfloat16")
    assert compute_dtype() == jnp.bfloat16
    monkeypatch.setenv(DTYPE_ENV, "float32")
    assert compute_dtype() == jnp.float32
    monkeypatch.setenv(DTYPE_ENV, "bogus")
    with pytest.raises(ValueError):
        compute_dtype()


def test_compute_dtype_arg_beats_env(monkeypatch):
    monkeypatch.setenv(DTYPE_ENV, "float32")
    assert compute_dtype("bf16") == jnp.bfloat16


def test_compute_dtype_default_fp32(monkeypatch):
    # fp32 is the measured-fastest TPU config (XLA already uses MXU bf16
    # passes for fp32 matmuls) and the exact config for CPU parity tests.
    monkeypatch.delenv(DTYPE_ENV, raising=False)
    assert compute_dtype() == jnp.float32


@pytest.mark.slow  # compile-heavy on 1-core CPU; full/CI run covers it
def test_rtdetr_bf16_outputs_fp32():
    """Heads are forced fp32 under bf16 compute (box/score mantissa)."""
    cfg = tiny_rtdetr_config()
    bf16 = RTDetrDetector(cfg, dtype=jnp.bfloat16)
    pixels = np.zeros((1, 64, 64, 3), np.float32)
    params = bf16.init(jax.random.PRNGKey(0), pixels)["params"]
    out = bf16.apply({"params": params}, pixels)
    assert out["pred_boxes"].dtype == jnp.float32
    assert out["logits"].dtype == jnp.float32


@pytest.mark.slow  # compile-heavy on 1-core CPU; full/CI run covers it
def test_detr_bf16_forward_close_to_fp32():
    """Same params, bf16 vs fp32 compute: pure rounding drift stays small.

    DETR is the family with no data-dependent query selection (RT-DETR's
    top-k selection is chaotic on a random-init model: near-tie scores make
    selected queries — not their values — differ between precisions, which
    is a test artifact, not a numerics defect). Box-refinement/sigmoid heads
    are pinned fp32, so remaining drift is bf16 matmul rounding only.
    """
    from spotter_tpu.models.detr import DetrDetector
    from spotter_tpu.models.zoo import tiny_detr_config

    cfg = tiny_detr_config()
    f32 = DetrDetector(cfg, dtype=jnp.float32)
    bf16 = DetrDetector(cfg, dtype=jnp.bfloat16)
    pixels = np.random.default_rng(0).standard_normal((2, 64, 64, 3)).astype(np.float32)
    params = f32.init(jax.random.PRNGKey(0), pixels[:1])["params"]

    out32 = f32.apply({"params": params}, pixels)
    out16 = bf16.apply({"params": params}, pixels)

    assert out16["pred_boxes"].dtype == jnp.float32
    assert out16["logits"].dtype == jnp.float32
    box_err = float(jnp.abs(out16["pred_boxes"] - out32["pred_boxes"]).max())
    # normalized coords: 3e-2 ≈ 2 px at the 64-px test scale, <<1% of image
    assert box_err < 3e-2, box_err


def test_gelu_auto_policy_bf16_error_bound():
    """The 'auto' GELU policy substitutes the tanh approximation on bf16
    tensors (models/layers.py: measured 14x cheaper on v5e). This ENFORCES
    the accepted deviation instead of assuming it (ADVICE r3): against
    exact-erf GELU evaluated on the SAME bf16-quantized input (input
    quantization is the tensor's pre-accepted bf16 state, not the
    activation policy's doing), the tanh approximation plus bf16 output
    rounding must stay within 1e-2 absolute everywhere in the MLP
    activation range, and within 2.5e-2 relative wherever the output is
    well-scaled (measured: 9.3e-3 abs at the +8 end; 2.3e-2 rel in the
    negative dip near x=-2.2 where gelu ~ -0.1)."""
    from spotter_tpu.models import layers

    x = np.concatenate(
        [
            np.linspace(-8.0, 8.0, 4001, dtype=np.float32),
            np.random.default_rng(0).standard_normal(4096).astype(np.float32) * 3,
        ]
    )
    xb = jnp.asarray(x, jnp.bfloat16)
    exact = np.asarray(jax.nn.gelu(xb.astype(jnp.float32), approximate=False))
    got = np.asarray(layers._gelu(xb), dtype=np.float32)
    err = np.abs(got - exact)
    assert err.max() <= 1e-2, err.max()
    scaled = np.abs(exact) > 0.1
    rel = err[scaled] / np.abs(exact[scaled])
    assert rel.max() <= 2.5e-2, rel.max()


def test_gelu_auto_policy_fp32_stays_exact():
    """On fp32 tensors 'auto' must remain bit-identical to exact erf — the
    parity-pinned serving default."""
    from spotter_tpu.models import layers

    x = jnp.asarray(
        np.random.default_rng(1).standard_normal(4096).astype(np.float32) * 3
    )
    np.testing.assert_array_equal(
        np.asarray(layers._gelu(x)),
        np.asarray(jax.nn.gelu(x, approximate=False)),
    )
