"""Precision policy: env/platform selection and bf16-vs-fp32 agreement.

The serving stack runs bf16 activations on TPU (MXU-native) with fp32 box
arithmetic in the heads; these tests pin the policy logic and check that a
bf16 forward stays close to the fp32 reference (the on-TPU analog of the
reference's ±1 px golden-box contract, test_serve.py:296-300).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spotter_tpu.models.rtdetr import RTDetrDetector
from spotter_tpu.models.zoo import tiny_rtdetr_config
from spotter_tpu.utils.precision import DTYPE_ENV, compute_dtype


def test_compute_dtype_env_override(monkeypatch):
    monkeypatch.setenv(DTYPE_ENV, "bfloat16")
    assert compute_dtype() == jnp.bfloat16
    monkeypatch.setenv(DTYPE_ENV, "float32")
    assert compute_dtype() == jnp.float32
    monkeypatch.setenv(DTYPE_ENV, "bogus")
    with pytest.raises(ValueError):
        compute_dtype()


def test_compute_dtype_arg_beats_env(monkeypatch):
    monkeypatch.setenv(DTYPE_ENV, "float32")
    assert compute_dtype("bf16") == jnp.bfloat16


def test_compute_dtype_default_fp32(monkeypatch):
    # fp32 is the measured-fastest TPU config (XLA already uses MXU bf16
    # passes for fp32 matmuls) and the exact config for CPU parity tests.
    monkeypatch.delenv(DTYPE_ENV, raising=False)
    assert compute_dtype() == jnp.float32


@pytest.mark.slow  # compile-heavy on 1-core CPU; full/CI run covers it
def test_rtdetr_bf16_outputs_fp32():
    """Heads are forced fp32 under bf16 compute (box/score mantissa)."""
    cfg = tiny_rtdetr_config()
    bf16 = RTDetrDetector(cfg, dtype=jnp.bfloat16)
    pixels = np.zeros((1, 64, 64, 3), np.float32)
    params = bf16.init(jax.random.PRNGKey(0), pixels)["params"]
    out = bf16.apply({"params": params}, pixels)
    assert out["pred_boxes"].dtype == jnp.float32
    assert out["logits"].dtype == jnp.float32


@pytest.mark.slow  # compile-heavy on 1-core CPU; full/CI run covers it
def test_detr_bf16_forward_close_to_fp32():
    """Same params, bf16 vs fp32 compute: pure rounding drift stays small.

    DETR is the family with no data-dependent query selection (RT-DETR's
    top-k selection is chaotic on a random-init model: near-tie scores make
    selected queries — not their values — differ between precisions, which
    is a test artifact, not a numerics defect). Box-refinement/sigmoid heads
    are pinned fp32, so remaining drift is bf16 matmul rounding only.
    """
    from spotter_tpu.models.detr import DetrDetector
    from spotter_tpu.models.zoo import tiny_detr_config

    cfg = tiny_detr_config()
    f32 = DetrDetector(cfg, dtype=jnp.float32)
    bf16 = DetrDetector(cfg, dtype=jnp.bfloat16)
    pixels = np.random.default_rng(0).standard_normal((2, 64, 64, 3)).astype(np.float32)
    params = f32.init(jax.random.PRNGKey(0), pixels[:1])["params"]

    out32 = f32.apply({"params": params}, pixels)
    out16 = bf16.apply({"params": params}, pixels)

    assert out16["pred_boxes"].dtype == jnp.float32
    assert out16["logits"].dtype == jnp.float32
    box_err = float(jnp.abs(out16["pred_boxes"] - out32["pred_boxes"]).max())
    # normalized coords: 3e-2 ≈ 2 px at the 64-px test scale, <<1% of image
    assert box_err < 3e-2, box_err
