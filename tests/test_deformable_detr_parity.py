"""Numerical parity: Flax DeformableDetrDetector vs HF torch
DeformableDetrForObjectDetection — tiny random-init configs, no network,
covering all three published variants (plain / with-box-refine / two-stage)
plus the single-scale config and the padded-pixel-mask path (valid ratios,
per-level mask sine embeddings, masked MSDA values)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from transformers import DeformableDetrConfig as HFDeformableDetrConfig
from transformers import ResNetConfig as HFResNetConfig
from transformers.models.deformable_detr.modeling_deformable_detr import (
    DeformableDetrForObjectDetection,
)

from spotter_tpu.convert.deformable_detr_rules import deformable_detr_rules
from spotter_tpu.convert.torch_to_jax import convert_state_dict
from spotter_tpu.models.configs import DeformableDetrConfig
from spotter_tpu.models.deformable_detr import DeformableDetrDetector


# torch/transformers parity and train/e2e files are the slow tier (VERDICT r1
# weak #6): the default `-m "not slow"` run must stay under 3 minutes.
pytestmark = pytest.mark.slow


def _tiny_hf_config(num_feature_levels=4, with_box_refine=False, two_stage=False):
    single = num_feature_levels == 1
    backbone = HFResNetConfig(
        embedding_size=8,
        hidden_sizes=[8, 12, 16, 24],
        depths=[1, 1, 1, 1],
        layer_type="basic",
        out_features=["stage4"] if single else ["stage2", "stage3", "stage4"],
    )
    return HFDeformableDetrConfig(
        use_timm_backbone=False,
        use_pretrained_backbone=False,
        backbone=None,
        backbone_config=backbone,
        d_model=32,
        encoder_layers=2,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        encoder_n_points=3,
        decoder_n_points=2,
        num_feature_levels=num_feature_levels,
        num_queries=11,
        num_labels=7,
        with_box_refine=with_box_refine,
        two_stage=two_stage,
        two_stage_num_proposals=9,
        disable_custom_kernels=True,
    )


def _run_parity(hf_cfg, with_mask: bool):
    torch.manual_seed(0)
    model = DeformableDetrForObjectDetection(hf_cfg).eval()
    with torch.no_grad():
        for m in model.modules():
            if hasattr(m, "running_mean"):
                m.running_mean.uniform_(-0.2, 0.2)
                m.running_var.uniform_(0.8, 1.2)

    cfg = DeformableDetrConfig.from_hf(hf_cfg)
    params = convert_state_dict(model.state_dict(), deformable_detr_rules(cfg), strict=True)

    rng = np.random.default_rng(1)
    x = rng.uniform(0, 1, size=(2, 3, 64, 96)).astype(np.float32)
    if with_mask:
        # ragged valid regions exercise valid ratios + per-level mask sines
        mask = np.zeros((2, 64, 96), dtype=np.int64)
        mask[0, :64, :80] = 1
        mask[1, :48, :96] = 1
    else:
        mask = np.ones((2, 64, 96), dtype=np.int64)

    with torch.no_grad():
        tout = model(torch.from_numpy(x), pixel_mask=torch.from_numpy(mask))

    jout = DeformableDetrDetector(cfg).apply(
        {"params": params},
        np.transpose(x, (0, 2, 3, 1)),
        mask.astype(np.float32) if with_mask else None,
    )

    np.testing.assert_allclose(
        np.asarray(jout["pred_boxes"]), tout.pred_boxes.numpy(), atol=5e-4, rtol=1e-3
    )
    np.testing.assert_allclose(
        np.asarray(jout["logits"]), tout.logits.numpy(), atol=1e-3, rtol=1e-3
    )
    if hf_cfg.two_stage:
        np.testing.assert_allclose(
            np.asarray(jout["enc_outputs_class"]),
            tout.enc_outputs_class.numpy(),
            atol=1e-3,
            rtol=1e-3,
        )


@pytest.mark.parametrize(
    "with_box_refine,two_stage",
    [(False, False), (True, False), (True, True)],
    ids=["plain", "box_refine", "two_stage"],
)
def test_deformable_detr_parity(with_box_refine, two_stage):
    _run_parity(
        _tiny_hf_config(with_box_refine=with_box_refine, two_stage=two_stage),
        with_mask=False,
    )


def test_deformable_detr_parity_masked():
    _run_parity(_tiny_hf_config(with_box_refine=True), with_mask=True)


def test_deformable_detr_parity_single_scale():
    _run_parity(_tiny_hf_config(num_feature_levels=1), with_mask=False)


def test_timm_backbone_mapping():
    """Published SenseTime/deformable-detr* checkpoints ship
    use_timm_backbone=true with backbone='resnet50'; the from_hf mapping and
    the 'timm' rule table must cover that path. (timm itself is absent here,
    so the torch side can't be instantiated — the config mapping and the rule
    table's key layout are pinned instead, mirroring
    test_table_transformer_parity.py::test_timm_resnet18_backbone_mapping.)"""
    hf = HFDeformableDetrConfig(num_labels=3)
    assert hf.use_timm_backbone and hf.backbone == "resnet50"
    cfg = DeformableDetrConfig.from_hf(hf)
    assert cfg.backbone.style == "v1" and cfg.backbone.layer_type == "bottleneck"
    assert cfg.backbone.out_indices == (2, 3, 4)  # strides 8/16/32

    torch_keys = {k for _, k, _ in deformable_detr_rules(cfg, "timm").rules}
    prefix = "model.backbone.conv_encoder.model."
    assert f"{prefix}conv1.weight" in torch_keys  # timm stem naming
    assert f"{prefix}layer4.2.conv3.weight" in torch_keys  # bottleneck depth 3
    assert f"{prefix}layer1.0.downsample.0.weight" in torch_keys
    # non-backbone half identical across namings
    hf_keys = {k for _, k, _ in deformable_detr_rules(cfg, "hf").rules}
    assert {k for k in torch_keys if not k.startswith(prefix)} == {
        k for k in hf_keys if not k.startswith(prefix)
    }

    single = HFDeformableDetrConfig(num_labels=3, num_feature_levels=1)
    assert DeformableDetrConfig.from_hf(single).backbone.out_indices == (4,)
