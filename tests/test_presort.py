"""Model-level locality presort (models/rtdetr.py): sorting the decoder's
queries once by initial reference centers and running all layers
`presorted=True` must be output-IDENTICAL to the unsorted model — it is a
pure permutation through permutation-equivariant layers, un-permuted at the
output. The kernel-side effect (skipping the in-op sort) is a sparsity
heuristic only; correctness is pinned here on the XLA backend, where the
permutation plumbing is the entire behavior change.
"""

import jax
import jax.numpy as jnp
import numpy as np

from spotter_tpu.models import rtdetr
from spotter_tpu.models.zoo import tiny_rtdetr_config


def test_presort_outputs_identical(monkeypatch):
    cfg = tiny_rtdetr_config(num_labels=7)
    model = rtdetr.RTDetrDetector(cfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (2, 64, 64, 3)), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)

    monkeypatch.setattr(rtdetr, "presort_wanted", lambda: False)
    base = model.apply(params, x)
    monkeypatch.setattr(rtdetr, "presort_wanted", lambda: True)
    sorted_out = model.apply(params, x)

    for k in ("logits", "pred_boxes", "aux_logits", "aux_boxes"):
        np.testing.assert_allclose(
            np.asarray(sorted_out[k]),
            np.asarray(base[k]),
            atol=2e-5,
            rtol=1e-4,
            err_msg=k,
        )


def test_presort_skipped_with_attention_mask(monkeypatch):
    """With a denoising-style self-attention mask the model must fall back
    to the in-op sort (the mask rows/cols are not permuted). The mask must
    be NON-uniform — a block-diagonal denoising mask — so that a wrongly
    applied presort (permuting queries under an un-permuted mask) would
    change outputs and fail this test."""
    cfg = tiny_rtdetr_config(num_labels=7)
    model = rtdetr.RTDetrDetector(cfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (1, 64, 64, 3)), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)

    # block-diagonal: first q//2 queries and the rest cannot attend across
    q = cfg.num_queries
    half = q // 2
    group = (jnp.arange(q) < half).astype(jnp.int32)
    blocked = group[:, None] != group[None, :]
    mask = jnp.where(blocked, -jnp.inf, 0.0)[None, None, :, :]
    monkeypatch.setattr(rtdetr, "presort_wanted", lambda: True)
    masked = model.apply(params, x, self_attention_mask=mask)
    monkeypatch.setattr(rtdetr, "presort_wanted", lambda: False)
    base = model.apply(params, x, self_attention_mask=mask)
    np.testing.assert_allclose(
        np.asarray(masked["logits"]), np.asarray(base["logits"]), atol=2e-5, rtol=1e-4
    )
