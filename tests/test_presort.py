"""Model-level locality presort (models/rtdetr.py): sorting the decoder's
queries once by initial reference centers and running all layers
`presorted=True` must be output-IDENTICAL to the unsorted model — it is a
pure permutation through permutation-equivariant layers, un-permuted at the
output. The kernel-side effect (skipping the in-op sort) is a sparsity
heuristic only; correctness is pinned here on the XLA backend, where the
permutation plumbing is the entire behavior change.
"""

import jax
import jax.numpy as jnp
import numpy as np

from spotter_tpu.models import deformable_detr, rtdetr
from spotter_tpu.models.configs import DeformableDetrConfig, ResNetConfig
from spotter_tpu.models.zoo import tiny_rtdetr_config


def test_presort_outputs_identical(monkeypatch):
    cfg = tiny_rtdetr_config(num_labels=7)
    model = rtdetr.RTDetrDetector(cfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (2, 64, 64, 3)), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)

    monkeypatch.setattr(rtdetr, "presort_wanted", lambda: False)
    base = model.apply(params, x)
    monkeypatch.setattr(rtdetr, "presort_wanted", lambda: True)
    sorted_out = model.apply(params, x)

    for k in ("logits", "pred_boxes", "aux_logits", "aux_boxes"):
        np.testing.assert_allclose(
            np.asarray(sorted_out[k]),
            np.asarray(base[k]),
            atol=2e-5,
            rtol=1e-4,
            err_msg=k,
        )


def test_presort_outputs_identical_deformable(monkeypatch):
    """Same exactness contract for the Deformable-DETR decoder presort
    (models/deformable_detr.py), two-stage + box-refine variant so the
    presorted refs flow through the full refinement path."""
    cfg = DeformableDetrConfig(
        backbone=ResNetConfig(
            style="v1",
            embedding_size=8,
            hidden_sizes=(8, 12, 16, 24),
            depths=(1, 1, 1, 1),
            layer_type="basic",
            out_indices=(2, 3, 4),
        ),
        num_labels=7,
        d_model=32,
        num_queries=12,
        encoder_layers=1,
        decoder_layers=2,
        encoder_attention_heads=4,
        decoder_attention_heads=4,
        encoder_ffn_dim=48,
        decoder_ffn_dim=48,
        num_feature_levels=4,
        encoder_n_points=2,
        decoder_n_points=2,
        with_box_refine=True,
        two_stage=True,
        two_stage_num_proposals=12,
    )
    model = deformable_detr.DeformableDetrDetector(cfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (2, 64, 64, 3)), jnp.float32)
    mask = jnp.ones((2, 64, 64), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x, mask)

    monkeypatch.setattr(deformable_detr, "presort_wanted", lambda: False)
    base = model.apply(params, x, mask)
    monkeypatch.setattr(deformable_detr, "presort_wanted", lambda: True)
    sorted_out = model.apply(params, x, mask)

    for k in ("logits", "pred_boxes", "aux_logits", "aux_boxes"):
        np.testing.assert_allclose(
            np.asarray(sorted_out[k]),
            np.asarray(base[k]),
            atol=2e-5,
            rtol=1e-4,
            err_msg=k,
        )


def test_presort_skipped_with_attention_mask(monkeypatch):
    """With a denoising-style self-attention mask the model must fall back
    to the in-op sort (the mask rows/cols are not permuted). The mask must
    be NON-uniform — a block-diagonal denoising mask — so that a wrongly
    applied presort (permuting queries under an un-permuted mask) would
    change outputs and fail this test."""
    cfg = tiny_rtdetr_config(num_labels=7)
    model = rtdetr.RTDetrDetector(cfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.uniform(0, 1, (1, 64, 64, 3)), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)

    # block-diagonal: first q//2 queries and the rest cannot attend across
    q = cfg.num_queries
    half = q // 2
    group = (jnp.arange(q) < half).astype(jnp.int32)
    blocked = group[:, None] != group[None, :]
    mask = jnp.where(blocked, -jnp.inf, 0.0)[None, None, :, :]
    monkeypatch.setattr(rtdetr, "presort_wanted", lambda: True)
    masked = model.apply(params, x, self_attention_mask=mask)
    monkeypatch.setattr(rtdetr, "presort_wanted", lambda: False)
    base = model.apply(params, x, self_attention_mask=mask)
    np.testing.assert_allclose(
        np.asarray(masked["logits"]), np.asarray(base["logits"]), atol=2e-5, rtol=1e-4
    )
