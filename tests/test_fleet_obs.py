"""Fleet observability plane (ISSUE 12): mergeable snapshots, cross-replica
aggregation, stitched fleet traces, and the fleet_top rendering.

Merge-math golden tests drive the PURE functions (merge_snapshots,
fleet_burn, fleet_mfu) with real Metrics-produced snapshots; the stateful
FleetAggregator is driven through observe()/mark_down() with no sockets;
the HTTP surfaces run real in-process topologies (stub replicas behind the
real router); and the cross-process stitching case reuses
testing/cluster.py so the replica's flight recorder is genuinely a
different process from the edge's.
"""

import asyncio
import json
import math
import os
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

os.environ["SPOTTER_TPU_TINY"] = "1"

from spotter_tpu import obs
from spotter_tpu.engine.batcher import MicroBatcher
from spotter_tpu.engine.metrics import (
    REPLICA_ID_ENV,
    STAGE_BUCKETS_MS,
    Metrics,
)
from spotter_tpu.obs import http as obs_http
from spotter_tpu.obs import prom
from spotter_tpu.obs.aggregate import (
    FleetAggregator,
    fleet_burn,
    fleet_mfu,
    merge_snapshots,
    quantile_from_hist,
)
from spotter_tpu.serving.detector import AmenitiesDetector
from spotter_tpu.serving.replica_pool import ReplicaPool
from spotter_tpu.serving.router import make_router_app
from spotter_tpu.serving.standalone import make_app
from spotter_tpu.testing import cluster
from spotter_tpu.testing.stub_engine import StubEngine, StubHttpClient

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")

# the keys the fleet plane ADDED to Metrics.snapshot() — the prom
# byte-stability pin below strips exactly these
MERGE_SUBSTRATE_KEYS = (
    "replica", "stage_ms_histogram", "slo_burn_raw", "perf_raw",
)


@pytest.fixture(autouse=True)
def fresh_recorder(monkeypatch):
    monkeypatch.delenv(obs.TRACE_RING_ENV, raising=False)
    monkeypatch.delenv(obs_http.ADMIN_TOKEN_ENV, raising=False)
    obs.reset_recorder()
    obs.set_current_trace(None)
    yield
    obs.reset_recorder()
    obs.set_current_trace(None)


def assert_nan_free(obj, path="$"):
    """Every float anywhere in the structure is finite — and the whole
    thing survives strict JSON (allow_nan=False), the acceptance bar."""
    if isinstance(obj, float):
        assert math.isfinite(obj), f"non-finite value at {path}"
    elif isinstance(obj, dict):
        for k, v in obj.items():
            assert_nan_free(v, f"{path}.{k}")
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            assert_nan_free(v, f"{path}[{i}]")


def _loaded_metrics(batches=3, latency_s=0.05, batch=4, sheds=0) -> Metrics:
    m = Metrics()
    for _ in range(batches):
        m.record_batch(
            batch, latency_s,
            stages={"device": latency_s * 0.8, "decode": latency_s * 0.1},
        )
    if sheds:
        m.record_shed(sheds)
    return m


# ---------------------------------------------------------------------------
# mergeable snapshots (satellites 1 + 2)


def test_snapshot_carries_identity_and_raw_stage_buckets():
    m = _loaded_metrics(batches=2)
    snap = m.snapshot()
    rep = snap["replica"]
    assert rep["pid"] == os.getpid()
    assert rep["replica_id"]
    assert rep["generation"] == 0
    assert rep["uptime_s"] >= 0.0
    assert rep["model"] is None  # stamped by the serving bootstrap
    # raw mergeable stage state alongside the point quantiles
    dev = snap["stage_ms_histogram"]["device"]
    assert dev["count"] == 2
    assert dev["sum"] == pytest.approx(2 * 0.05 * 0.8 * 1e3, rel=1e-6)
    assert len(dev["buckets"]) == len(STAGE_BUCKETS_MS)
    assert dev["buckets"][-1][0] is None  # +Inf bound serialized as null
    assert dev["buckets"][-1][1] == 2  # cumulative
    assert "stage_device_ms_p50" in snap  # point summary unchanged
    # SLO burn + MFU raw state ride the perf block
    assert "buckets" in snap["slo_burn_raw"]
    assert snap["perf_raw"]["window_span_s"] >= 0.0


def test_identity_env_override_and_generation_via_restarts(monkeypatch):
    monkeypatch.setenv(REPLICA_ID_ENV, "pod-7")
    m = Metrics()
    m.set_restarts(3)
    m.set_identity(model="rtdetr_v2_r101vd")
    rep = m.snapshot()["replica"]
    assert rep["replica_id"] == "pod-7"
    assert rep["generation"] == 3  # restart count IS the reset generation
    assert rep["model"] == "rtdetr_v2_r101vd"


def test_prom_exposition_byte_stable_despite_merge_substrate():
    """The raw merge state is JSON-only: the Prometheus rendering of a
    snapshot is byte-identical with and without it (satellite 1's
    'keep the prom summary rendering byte-stable' pin)."""
    m = _loaded_metrics(batches=4, sheds=2)
    snap = m.snapshot()
    for key in MERGE_SUBSTRATE_KEYS:
        assert key in snap, f"snapshot lost merge-substrate key {key}"
    stripped = {k: v for k, v in snap.items() if k not in MERGE_SUBSTRATE_KEYS}
    assert prom.render(snap) == prom.render(stripped)
    # the pre-existing stage summary gauges still render
    assert "spotter_tpu_stage_device_ms_p50" in prom.render(snap)


# ---------------------------------------------------------------------------
# merge math goldens (pure functions)


def test_merged_counters_equal_sum_of_members():
    ms = [
        _loaded_metrics(batches=2, batch=4, sheds=1),
        _loaded_metrics(batches=3, batch=2),
        _loaded_metrics(batches=1, batch=8, sheds=4),
    ]
    snaps = [m.snapshot() for m in ms]
    fleet = merge_snapshots(snaps)
    for key in ("images_total", "batches_total", "shed_total",
                "errors_total", "cache_hits_total"):
        assert fleet[key] == sum(s[key] for s in snaps), key
    hist = fleet["latency_ms_histogram"]
    assert hist["count"] == sum(
        s["latency_ms_histogram"]["count"] for s in snaps
    )
    assert hist["sum"] == pytest.approx(
        sum(s["latency_ms_histogram"]["sum"] for s in snaps)
    )
    # stage raw buckets add too
    assert fleet["stage_ms_histogram"]["device"]["count"] == sum(
        s["stage_ms_histogram"]["device"]["count"] for s in snaps
    )
    assert_nan_free(fleet)


def test_fleet_quantiles_recomputed_from_buckets_not_averaged():
    fast = _loaded_metrics(batches=10, latency_s=0.020)  # le=25 bucket
    slow = _loaded_metrics(batches=10, latency_s=0.200)  # le=250 bucket
    s_fast, s_slow = fast.snapshot(), slow.snapshot()
    fleet = merge_snapshots([s_fast, s_slow])
    # 20 samples, half at 20 ms, half at 200 ms: the merged-histogram p50
    # lands on the 25 ms bucket bound. An averaged-averages "p50" would be
    # (20 + 200) / 2 = 110 ms — pinned wrong here.
    assert fleet["latency_ms_p50"] == 25.0
    naive = (s_fast["latency_ms_p50"] + s_slow["latency_ms_p50"]) / 2
    assert abs(fleet["latency_ms_p50"] - naive) > 50.0
    assert fleet["latency_ms_p99"] == 250.0
    # quantile helper is NaN-free on empty
    assert quantile_from_hist({"buckets": [], "count": 0}, 0.5) == 0.0


def test_fleet_burn_recomputed_from_merged_buckets():
    loud = _loaded_metrics(batches=9, batch=10)  # 90 good
    loud.record_shed(10)  # 10 bad
    quiet = Metrics()  # zero traffic
    fleet = merge_snapshots([loud.snapshot(), quiet.snapshot()])
    # merged: 10 bad / 100 events = 0.1 ratio over a 1% budget -> burn 10.
    # An average of member burns would halve it (quiet member burns 0).
    assert fleet["slo_burn_rate"]["fast"] == pytest.approx(10.0, abs=0.01)
    rates, target = fleet_burn(
        [loud.snapshot()["slo_burn_raw"], quiet.snapshot()["slo_burn_raw"]]
    )
    assert rates["fast"] == pytest.approx(10.0, abs=0.01)
    assert target == 99.0


def test_fleet_mfu_weighted_by_span_times_peak_not_averaged():
    a = {"window_span_s": 60.0, "device_s": 30.0, "flops": 100e12,
         "useful_flops": 50e12, "peak_flops": 200e12}
    b = {"window_span_s": 30.0, "device_s": 15.0, "flops": 30e12,
         "useful_flops": 30e12, "peak_flops": 100e12}
    out = fleet_mfu([a, b])
    # sum(flops) / sum(span x peak) = 130e12 / 1.5e16 = 0.8667%
    assert out["mfu_pct"] == pytest.approx(0.867, abs=1e-3)
    mfu_a = 100 * 100e12 / (60 * 200e12)  # 0.833
    mfu_b = 100 * 30e12 / (30 * 100e12)  # 1.0
    assert abs(out["mfu_pct"] - (mfu_a + mfu_b) / 2) > 0.04
    # members with no peak (stub engines) contribute duty but never MFU
    out2 = fleet_mfu([{"window_span_s": 10.0, "device_s": 5.0,
                       "flops": 0.0, "useful_flops": 0.0,
                       "peak_flops": 0.0}])
    assert out2["mfu_pct"] == 0.0
    assert out2["device_duty_cycle_pct"] == pytest.approx(50.0)


# ---------------------------------------------------------------------------
# aggregator state machine: resets, staleness, NaN-free at 0/1/N


def test_generation_bump_folds_counters_never_negative():
    agg = FleetAggregator(lambda: ["http://a"], interval_s=30.0)
    gen0 = _loaded_metrics(batches=5, batch=4)  # 20 images
    snap0 = gen0.snapshot()
    agg.observe("http://a", snap0)
    assert agg.fleet_snapshot()["images_total"] == 20
    # the replica restarts: generation bumps, counters restart near zero
    gen1 = _loaded_metrics(batches=1, batch=2)  # 2 images
    snap1 = gen1.snapshot()
    snap1["replica"] = dict(snap1["replica"], generation=1)
    agg.observe("http://a", snap1)
    fleet = agg.fleet_snapshot()
    assert fleet["images_total"] == 22  # 20 retained + 2 new, monotone
    assert fleet["replicas"]["generation_resets_total"] == 1
    # next scrape of the SAME generation does not double-fold
    gen1.record_batch(2, 0.01)
    snap1b = gen1.snapshot()
    snap1b["replica"] = dict(snap1b["replica"], generation=1)
    agg.observe("http://a", snap1b)
    assert agg.fleet_snapshot()["images_total"] == 24
    assert agg.fleet_snapshot()["replicas"]["generation_resets_total"] == 1


def test_counter_regression_without_generation_also_folds():
    """Defense in depth: a replica replaced behind the same URL without a
    generation source still must not drag fleet counters backwards."""
    agg = FleetAggregator(lambda: ["http://a"], interval_s=30.0)
    big = _loaded_metrics(batches=10, batch=4).snapshot()  # 40 images
    small = _loaded_metrics(batches=1, batch=1).snapshot()  # 1 image
    # strip the generation signal entirely
    big.pop("replica")
    small.pop("replica")
    agg.observe("http://a", big)
    agg.observe("http://a", small)
    assert agg.fleet_snapshot()["images_total"] == 41


def test_fleet_snapshot_nan_free_at_zero_one_n_members():
    # zero members ever seen
    empty = FleetAggregator(lambda: [], interval_s=30.0).fleet_snapshot()
    assert_nan_free(empty)
    json.dumps(empty, allow_nan=False)
    assert empty["images_per_sec"] == 0
    assert empty["slo_burn_rate"] == {"fast": 0.0, "slow": 0.0}
    assert empty["mfu_pct"] == 0.0
    # one member
    agg1 = FleetAggregator(lambda: ["http://a"], interval_s=30.0)
    agg1.observe("http://a", _loaded_metrics().snapshot())
    assert_nan_free(agg1.fleet_snapshot())
    # N members, one dying mid-scrape
    agg = FleetAggregator(lambda: ["http://a", "http://b", "http://c"],
                          interval_s=30.0)
    for u in ("http://a", "http://b", "http://c"):
        agg.observe(u, _loaded_metrics(batches=2).snapshot())
    before = agg.fleet_snapshot()
    agg.mark_down("http://b", "ConnectError('killed mid-scrape')")
    fleet = agg.fleet_snapshot()
    assert_nan_free(fleet)
    json.dumps(fleet, allow_nan=False)
    assert fleet["replicas"]["up"] == 2
    assert fleet["replicas"]["stale"] == 1
    # counters keep the dead member's history — cumulative facts
    assert fleet["images_total"] == before["images_total"]
    row = next(r for r in fleet["per_replica"] if r["url"] == "http://b")
    assert row["up"] is False and row["stale"] is True
    assert "killed mid-scrape" in row["last_error"]


def test_stale_member_drops_out_of_gauges_keeps_counters():
    agg = FleetAggregator(
        lambda: ["http://a", "http://b"], interval_s=30.0,
        stale_after_s=0.05,
    )
    busy = _loaded_metrics(batches=5, batch=4).snapshot()
    agg.observe("http://a", busy)
    agg.observe("http://b", busy)
    fresh = agg.fleet_snapshot()
    assert fresh["replicas"]["up"] == 2
    assert fresh["images_per_sec"] > 0
    time.sleep(0.08)  # both members go stale (no successful scrape since)
    stale = agg.fleet_snapshot()
    assert stale["replicas"]["up"] == 0
    assert stale["replicas"]["stale"] == 2
    # gauges emptied (a dead fleet is not still "serving" its last rate);
    # counters retained
    assert stale["images_per_sec"] == 0
    assert stale["images_total"] == fresh["images_total"]
    assert all(r["stale"] for r in stale["per_replica"])
    assert_nan_free(stale)


# ---------------------------------------------------------------------------
# HTTP surfaces: fleet /metrics, /debug/fleet, chaos mid-scrape


def _stub_detector(service_ms: float = 0.0) -> AmenitiesDetector:
    engine = StubEngine(service_ms=service_ms)
    return AmenitiesDetector(
        engine, MicroBatcher(engine, max_delay_ms=1.0), StubHttpClient()
    )


async def _stub_fleet(n: int):
    dets, servers, urls = [], [], []
    for _ in range(n):
        det = _stub_detector()
        server = TestServer(make_app(detector=det))
        await server.start_server()
        dets.append(det)
        servers.append(server)
        urls.append(f"http://{server.host}:{server.port}")
    return dets, servers, urls


def test_router_fleet_metrics_merge_and_prom_labels(monkeypatch):
    async def run():
        dets, servers, urls = await _stub_fleet(2)
        pool = ReplicaPool(urls, health_interval_s=0.25)
        # long interval: enabled (fleet block present) but the background
        # task won't race the manual scrape_once calls below
        agg = FleetAggregator(lambda: urls, interval_s=30.0)
        app = make_router_app(pool, aggregator=agg)
        async with TestClient(TestServer(app)) as client:
            for i in range(8):
                resp = await client.post(
                    "/detect",
                    json={"image_urls": [f"http://img/{i % 3}.jpg"]},
                )
                assert resp.status == 200
            await agg.scrape_once()
            snap = json.loads(await (await client.get("/metrics")).read())
            fleet = snap["fleet"]
            member_sum = sum(
                d.engine.metrics.snapshot()["images_total"] for d in dets
            )
            assert fleet["images_total"] == member_sum == 8
            assert fleet["replicas"]["up"] == 2
            assert fleet["brownout_rung"] == 0
            rows = {r["url"]: r for r in fleet["per_replica"]}
            assert set(rows) == set(urls)
            assert all(r["model"] == "stub" for r in rows.values())
            assert_nan_free(fleet)
            # prom exposition: fleet counters + per-replica {url} labels
            text = await (
                await client.get("/metrics?format=prometheus")
            ).text()
            assert "spotter_tpu_fleet_images_total 8" in text
            assert (
                f'spotter_tpu_fleet_per_replica_images_total{{url="{urls[0]}"}}'
                in text
            )
            assert "spotter_tpu_fleet_slo_burn_rate" in text

            # chaos: kill one replica mid-scrape — the fleet surface stays
            # NaN-free and the member is marked down/stale
            await servers[0].close()
            await agg.scrape_once()
            snap2 = json.loads(await (await client.get("/metrics")).read())
            fleet2 = snap2["fleet"]
            assert_nan_free(fleet2)
            json.dumps(fleet2, allow_nan=False)
            assert fleet2["replicas"]["up"] == 1
            assert fleet2["replicas"]["stale"] == 1
            dead = next(
                r for r in fleet2["per_replica"] if r["url"] == urls[0]
            )
            assert dead["up"] is False
            # counter history survives the death
            assert fleet2["images_total"] == member_sum
        for server in servers[1:]:
            await server.close()
        for det in dets:
            await det.aclose()

    asyncio.run(run())


def test_debug_fleet_admin_gated(monkeypatch):
    async def run():
        dets, servers, urls = await _stub_fleet(1)
        pool = ReplicaPool(urls, health_interval_s=0.25)
        agg = FleetAggregator(lambda: urls, interval_s=30.0)
        app = make_router_app(pool, aggregator=agg)
        async with TestClient(TestServer(app)) as client:
            await agg.scrape_once()
            monkeypatch.setenv(obs_http.ADMIN_TOKEN_ENV, "sekrit")
            resp = await client.get("/debug/fleet")
            assert resp.status == 401
            resp = await client.get(
                "/debug/fleet", headers={"X-Admin-Token": "sekrit"}
            )
            assert resp.status == 200
            body = json.loads(await resp.read())
            assert body["replicas"]["up"] == 1
            row = body["per_replica"][0]
            for key in ("url", "images_per_sec", "latency_ms_p99",
                        "slo_burn_fast", "mfu_pct", "hbm_bytes_in_use",
                        "brownout_rung", "cache_hit_rate", "generation"):
                assert key in row, key
        for server in servers:
            await server.close()
        for det in dets:
            await det.aclose()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# cross-replica trace stitching (the replica is a REAL subprocess, so its
# flight recorder is genuinely not the edge's)


@pytest.fixture(scope="module")
def slow_replica(tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("fleet-obs-replica"))
    replicas = cluster.start_replicas(
        1, workdir,
        env={"SPOTTER_TPU_FAULTS": "slow_stage=device:120"},
    )
    try:
        yield replicas[0]
    finally:
        for r in replicas:
            r.shutdown()


def test_fleet_trace_stitching_end_to_end(slow_replica):
    replica = slow_replica

    async def run():
        pool = ReplicaPool([replica.url])
        agg = FleetAggregator(lambda: [replica.url], interval_s=30.0)
        app = make_router_app(pool, aggregator=agg)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post(
                "/detect",
                json={"image_urls": ["http://img/slow.jpg"]},
                headers={"X-Request-ID": "fleet-stitch-1"},
            )
            assert resp.status == 200
            # the slowest-K list view stitches the injected-slow request
            resp = await client.get("/debug/traces?fleet=1")
            assert resp.status == 200
            payload = json.loads(await resp.read())
            assert payload["fleet"] is True
            assert payload["stitched"], "no stitched trees"
            tree = payload["stitched"][0]
            edge_spans = {s["name"] for s in tree["edge"]["spans"]}
            assert obs.ROUTE in edge_spans
            assert tree["replicas"], "no replica joined the edge trace"
            joined = tree["replicas"][0]
            assert joined["url"] == replica.url
            rep_trace = joined["traces"][0]
            assert rep_trace["trace_id"] == tree["edge"]["trace_id"]
            device = [
                s for s in rep_trace["spans"] if s["name"] == obs.DEVICE
            ]
            assert device and device[0]["duration_ms"] >= 100.0
            # by-id lookup returns the same single tree; a bogus id is 404
            tid = tree["edge"]["trace_id"]
            resp = await client.get(f"/debug/traces?fleet=1&trace_id={tid}")
            assert resp.status == 200
            one = json.loads(await resp.read())
            assert len(one["stitched"]) >= 1
            resp = await client.get("/debug/traces?fleet=1&trace_id=" + "0" * 32)
            assert resp.status == 404
        await agg.stop()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# fleet_top rendering (pure)


def test_fleet_top_render():
    from tools.fleet_top import render

    snapshot = {
        "fleet": {
            "replicas": {"seen": 2, "up": 1, "stale": 1,
                         "generation_resets_total": 3},
            "images_per_sec": 123.4,
            "latency_ms_p99": 87.5,
            "slo_burn_rate": {"fast": 1.25, "slow": 0.5},
            "mfu_pct": 42.0,
            "brownout_rung": 2,
            "per_replica": [
                {"url": "http://r1:8000", "up": True, "stale": False,
                 "generation": 1, "model": "rtdetr_v2_r101vd",
                 "images_per_sec": 100.0, "latency_ms_p50": 20.0,
                 "latency_ms_p99": 55.0, "slo_burn_fast": 0.9,
                 "mfu_pct": 44.0, "device_duty_cycle_pct": 70.0,
                 "cache_hit_rate": 0.82, "brownout_rung": 0},
                {"url": "http://r2:8000", "up": False, "stale": True,
                 "generation": 2, "model": None,
                 "images_per_sec": 0.0, "latency_ms_p50": 0.0,
                 "latency_ms_p99": 0.0, "slo_burn_fast": 0.0,
                 "mfu_pct": 0.0, "device_duty_cycle_pct": 0.0,
                 "cache_hit_rate": 0.0, "brownout_rung": 0},
            ],
        }
    }
    out = render(snapshot)
    lines = out.splitlines()
    assert "1/2 up" in lines[0] and "burn 1.25/0.50" in lines[0]
    assert "REPLICA" in lines[2] and "RUNG" in lines[2]
    r1 = next(ln for ln in lines if "http://r1:8000" in ln)
    assert "ready" in r1 and "rtdetr_v2_r101" in r1 and "82" in r1
    r2 = next(ln for ln in lines if "http://r2:8000" in ln)
    assert "down" in r2
    # an edge without the aggregator armed is reported, not rendered empty
    assert "aggregator" in render({"pool_requests_total": 0})


def test_fleet_top_render_autoscale_pools():
    from tools.fleet_top import _autoscale_lines, render

    snapshot = {
        "fleet": {"replicas": {"seen": 0, "up": 0, "stale": 0,
                               "generation_resets_total": 0},
                  "per_replica": []},
        "autoscale": {
            "decisions_total": 7,
            "scale_ups_total": 2,
            "scale_downs_total": 1,
            "wakes_total": 1,
            "flood_suppressions_total": 3,
            "routing_rejections_total": 4,
            "default_pool": "rtdetr",
            "pools": {
                "rtdetr": {
                    "model": "rtdetr", "open_vocab": False,
                    "tp": 1, "dp": 2, "desired": 2, "ready": 2,
                    "scaled_to_zero": False, "restoring": False,
                    "time_to_ready_s": 0.42, "admits_total": 19,
                    "inflight": 1,
                    "last_decision": {"current": 1, "desired": 2,
                                      "reason": "up: queue 5.0",
                                      "age_s": 12.3},
                },
                "owlvit": {
                    "model": "owlvit", "open_vocab": True,
                    "tp": 2, "dp": 1, "desired": 0, "ready": 0,
                    "scaled_to_zero": True, "restoring": False,
                    "time_to_ready_s": None, "admits_total": 0,
                    "inflight": 0, "last_decision": None,
                },
                "yolos": {
                    "model": "yolos", "open_vocab": False,
                    "tp": 1, "dp": 1, "desired": 1, "ready": 0,
                    "scaled_to_zero": False, "restoring": True,
                    "time_to_ready_s": None, "admits_total": 3,
                    "inflight": 0, "last_decision": None,
                },
            },
        },
    }
    out = render(snapshot)
    lines = out.splitlines()
    totals = next(ln for ln in lines if ln.startswith("autoscale:"))
    assert "7 decisions (2 up, 1 down, 1 wakes)" in totals
    assert "flood holds 3" in totals and "routing 400s 4" in totals
    assert "default rtdetr" in totals
    header = next(ln for ln in lines if "LAST DECISION" in ln)
    assert "POOL" in header and "DES" in header and "TTR_S" in header
    rt = next(ln for ln in lines if ln.startswith("rtdetr"))
    assert "tp1xdp2" in rt and "0.42" in rt
    assert "1->2 up: queue 5.0 (12s ago)" in rt
    owl = next(ln for ln in lines if ln.startswith("owlvit"))
    assert "owlvit*" in owl and "zero" in owl and "tp2xdp1" in owl
    yo = next(ln for ln in lines if ln.startswith("yolos"))
    assert "restoring" in yo
    # pool rows sort by name regardless of dict order
    assert lines.index(owl) < lines.index(rt) < lines.index(yo)
    # absent-plane discipline: no autoscale block, no autoscale lines
    assert _autoscale_lines({"fleet": {}}) == []
    assert "autoscale:" not in render({"fleet": snapshot["fleet"]})
