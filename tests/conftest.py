"""Test harness: CPU backend with an 8-device virtual mesh.

SURVEY.md §4.4: multi-chip behavior is tested without hardware via
`--xla_force_host_platform_device_count` — the moral equivalent of the
reference's fake k8s dynamic client (handlers_test.go:19-20). These env vars
must be set before jax is first imported, hence module scope here.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Surface NaNs produced inside jit in tests (SURVEY.md §5.2).
os.environ.setdefault("JAX_DEBUG_NANS", "False")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)
