"""Test harness: CPU backend with an 8-device virtual mesh.

SURVEY.md §4.4: multi-chip behavior is tested without hardware via
`--xla_force_host_platform_device_count` — the moral equivalent of the
reference's fake k8s dynamic client (handlers_test.go:19-20). These env vars
must be set before jax is first imported, hence module scope here.
"""

import os
import sys

# Force CPU (the session env may point JAX at a real TPU; tests must be
# hermetic and run the virtual 8-device mesh).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# NaN checking is off by default (it disables some fusions and slows the
# 1-core CPU runs); individual numerical tests opt in via the `debug_nans`
# fixture below (SURVEY §5.2 — adopters: test_train.py, test_postprocess.py).
os.environ.setdefault("JAX_DEBUG_NANS", "False")
# Parity tests compare against fp32 torch; JAX's CPU backend defaults to a
# lower-precision oneDNN path (~1e-2 drift per conv), so pin full precision.
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

# The session interpreter may carry a TPU-tunnel PJRT plugin ("axon") whose
# registration hook initializes the remote backend from ANY jax process — and
# hangs every test when the tunnel is unhealthy. Tests are CPU-only by design;
# drop the plugin factory and its discovery env before the first backend init.
for _var in ("PJRT_LIBRARY_PATH", "PJRT_NAMES_AND_LIBRARY_PATHS"):
    os.environ.pop(_var, None)
try:
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    # The session interpreter imports jax at startup (sitecustomize), so the
    # env vars above may be read already — set the live config too.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")
except Exception:
    pass

import pytest  # noqa: E402


@pytest.fixture
def debug_nans():
    """Run a test with `jax_debug_nans` enabled (SURVEY §5.2).

    Any NaN produced inside jitted or eager numerics fails the test at the
    producing op instead of propagating into an assertion tolerance miss.
    Opt-in per test: it disables some fusions and re-runs de-optimized code on
    hit, too slow to be the suite-wide default on the 1-core CPU runner.
    """
    import jax

    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)
