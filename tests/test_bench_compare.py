"""tools/bench_compare.py (ISSUE 10): the bench regression gate and its
schema guard — a malformed bench record must fail loudly (exit 2), never
silently pass the gate."""

import json

import pytest

from tools.bench_compare import (
    compare,
    extract_record,
    load_record,
    main,
    validate_record,
)

GOOD = {
    "metric": "model images/sec/chip",
    "value": 264.2,
    "unit": "images/sec",
    "vs_baseline": 0.528,
}


def _write(tmp_path, name, payload):
    path = tmp_path / name
    path.write_text(json.dumps(payload))
    return str(path)


def test_extract_unwraps_the_evidence_shape():
    wrapper = {"n": 5, "cmd": "python bench.py", "rc": 0, "parsed": GOOD}
    assert extract_record(wrapper) == GOOD
    assert extract_record(GOOD) == GOOD
    assert extract_record([1, 2]) is None


def test_validate_accepts_good_and_null_baseline():
    assert validate_record(GOOD, "x") == []
    ok_null = dict(GOOD, vs_baseline=None)
    assert validate_record(ok_null, "x") == []


@pytest.mark.parametrize(
    "mutation, fragment",
    [
        ({"metric": 5}, "key 'metric'"),
        ({"value": "264"}, "key 'value'"),
        ({"value": float("nan")}, "key 'value'"),
        ({"value": True}, "key 'value'"),
        ({"unit": ""}, "key 'unit'"),
        ({"vs_baseline": "x"}, "key 'vs_baseline'"),
    ],
)
def test_validate_rejects_badly_typed_fields(mutation, fragment):
    record = dict(GOOD, **mutation)
    problems = validate_record(record, "BENCH_bad.json")
    assert problems and any(fragment in p for p in problems)
    assert all(p.startswith("BENCH_bad.json") for p in problems)


def test_validate_reports_every_missing_key():
    problems = validate_record({}, "x")
    assert len(problems) == 4  # one readable line per missing field


def test_compare_regression_gate():
    old = dict(GOOD, value=100.0)
    flat = compare(old, dict(GOOD, value=96.0), threshold_pct=5.0)
    assert not flat["regression"]  # -4% inside the 5% tolerance
    reg = compare(old, dict(GOOD, value=90.0), threshold_pct=5.0)
    assert reg["regression"] and reg["delta_pct"] == -10.0
    gain = compare(old, dict(GOOD, value=120.0), threshold_pct=5.0)
    assert not gain["regression"]


def test_compare_lower_is_better_flips_direction():
    old = dict(GOOD, value=100.0, unit="ms")
    worse = compare(
        old, dict(GOOD, value=110.0, unit="ms"), 5.0, lower_is_better=True
    )
    assert worse["regression"]
    better = compare(
        old, dict(GOOD, value=90.0, unit="ms"), 5.0, lower_is_better=True
    )
    assert not better["regression"]


def test_main_exit_codes(tmp_path, capsys):
    old = _write(tmp_path, "old.json", dict(GOOD, value=100.0))
    ok = _write(tmp_path, "ok.json", dict(GOOD, value=101.0))
    reg = _write(tmp_path, "reg.json", dict(GOOD, value=90.0))
    bad = _write(tmp_path, "bad.json", {"metric": "m", "unit": "images/sec"})
    other_unit = _write(tmp_path, "unit.json", dict(GOOD, unit="ms"))

    assert main([old, ok]) == 0
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["regression"] is False

    assert main([old, reg]) == 1  # the synthetic 10% regression gate
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["regression"] is True and verdict["delta_pct"] == -10.0

    assert main([old, bad]) == 2  # schema guard: loud, not a silent pass
    out = capsys.readouterr()
    payload = json.loads(out.out.strip().splitlines()[-1])
    assert payload["error"] == "schema"
    assert any("missing key 'value'" in p for p in payload["problems"])

    assert main([old, other_unit]) == 2  # apples-to-oranges refused
    assert "unit mismatch" in capsys.readouterr().err


def test_main_handles_unreadable_file(tmp_path, capsys):
    old = _write(tmp_path, "old.json", GOOD)
    garbage = tmp_path / "garbage.json"
    garbage.write_text("{not json")
    assert main([old, str(garbage)]) == 2
    assert "unreadable" in capsys.readouterr().err


def test_load_record_roundtrip_on_repo_evidence(tmp_path):
    # the committed BENCH_r04/r05 evidence wrappers must satisfy the guard
    # (the CI self-check depends on it)
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for name in ("BENCH_r04.json", "BENCH_r05.json"):
        record, problems = load_record(os.path.join(repo, name))
        assert problems == [], problems
        assert record["unit"] == "images/sec"
