"""bisect_top_k == lax.top_k, bitwise (values AND indices, incl. ties)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spotter_tpu.ops.topk import bisect_top_k


@pytest.mark.parametrize("shape,k", [((4, 97), 13), ((2, 8400), 300), ((1, 50), 50)])
def test_matches_lax_top_k_random(shape, k):
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal(shape).astype(np.float32)
    )
    v_ref, i_ref = jax.lax.top_k(x, k)
    v, i = jax.jit(bisect_top_k, static_argnums=1)(x, k)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(v_ref))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))


def test_matches_with_massive_ties():
    # quantized scores: many exact ties across the k boundary
    rng = np.random.default_rng(1)
    x = jnp.asarray(np.round(rng.standard_normal((3, 500)) * 4) / 4, jnp.float32)
    v_ref, i_ref = jax.lax.top_k(x, 40)
    v, i = bisect_top_k(x, 40)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(v_ref))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))


def test_matches_with_negatives_zeros_infs():
    x = jnp.asarray(
        [[0.0, -0.0, 1.5, -1.5, np.inf, -np.inf, 2.0, 2.0, -3.0, 0.25]],
        jnp.float32,
    )
    v_ref, i_ref = jax.lax.top_k(x, 6)
    v, i = bisect_top_k(x, 6)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(v_ref))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))


def test_bf16_inputs_match():
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 300)), jnp.bfloat16
    )
    v_ref, i_ref = jax.lax.top_k(x, 25)
    v, i = bisect_top_k(x, 25)
    np.testing.assert_array_equal(
        np.asarray(v, np.float32), np.asarray(v_ref, np.float32)
    )
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))


def test_grad_flows_through_values():
    # selection indices are integer outputs; values must be differentiable
    # like lax.top_k's (gather from input)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 64)), jnp.float32)

    def f(x):
        v, _ = bisect_top_k(x, 5)
        return (v * jnp.arange(1.0, 6.0)).sum()

    def f_ref(x):
        v, _ = jax.lax.top_k(x, 5)
        return (v * jnp.arange(1.0, 6.0)).sum()

    np.testing.assert_allclose(
        np.asarray(jax.grad(f)(x)), np.asarray(jax.grad(f_ref)(x)), atol=1e-6
    )
