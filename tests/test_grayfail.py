"""Gray-failure immunity (ISSUE 14): latency outlier scoring + soft
ejection, budgeted adaptive hedging (loser cancellation, budget
exhaustion, affinity composition), the X-Spotter-Replica identity header,
and the deterministic chaos matrix. Replicas are tiny in-process aiohttp
servers (the test_replica_pool pattern) or full stub-detector apps (the
chaos matrix) — model-free, CPU-safe."""

import asyncio
import time

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from spotter_tpu.serving.replica_pool import (
    CANARY_OK_REQUIRED,
    OUTLIER_CANARY,
    OUTLIER_GRAY,
    OUTLIER_OK,
    ReplicaPool,
    RetryBudget,
)
from spotter_tpu.serving.resilience import Ewma

PAYLOAD = {"image_urls": ["http://example.com/room.jpg"]}


class ScriptedReplica:
    """In-process /detect + /healthz server: scriptable latency for both
    routes, cancellation tracking on /detect (the hedge-loser contract)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.status = 200
        self.delay_s = 0.0
        self.health_delay_s = 0.0
        self.health_status = 200
        self.detect_calls = 0
        self.cancelled = 0
        app = web.Application()
        app.router.add_post("/detect", self._detect)
        app.router.add_get("/healthz", self._healthz)
        self.server = TestServer(app)

    async def _detect(self, request: web.Request) -> web.Response:
        self.detect_calls += 1
        try:
            if self.delay_s:
                await asyncio.sleep(self.delay_s)
        except asyncio.CancelledError:
            # the hedge loser's socket was torn down mid-service: the
            # aiohttp handler task is cancelled when the client disconnects
            self.cancelled += 1
            raise
        return web.json_response({"served_by": self.name}, status=self.status)

    async def _healthz(self, request: web.Request) -> web.Response:
        if self.health_delay_s:
            await asyncio.sleep(self.health_delay_s)
        return web.json_response({}, status=self.health_status)

    async def start(self) -> str:
        await self.server.start_server()
        return f"http://{self.server.host}:{self.server.port}"

    async def stop(self) -> None:
        await self.server.close()


async def _with_replicas(n):
    replicas = [ScriptedReplica(f"r{i}") for i in range(n)]
    urls = [await r.start() for r in replicas]
    return replicas, urls


# ---- outlier scoring units -------------------------------------------------


def test_ewma_warmup_and_smoothing():
    e = Ewma(alpha=0.5)
    assert e.samples == 0 and e.value == 0.0
    assert e.update(100.0) == 100.0  # first sample seeds, no smoothing
    assert e.update(0.0) == 50.0
    assert e.samples == 2
    e.reset()
    assert e.samples == 0 and e.value == 0.0


def _quiet_pool(urls=None, **kwargs) -> ReplicaPool:
    """A pool that never talks to the network (health loop not started)."""
    kwargs.setdefault("health_interval_s", 30.0)
    return ReplicaPool(urls or ["http://10.0.0.1:1", "http://10.0.0.2:1",
                                "http://10.0.0.3:1"], **kwargs)


def test_outlier_trips_gray_then_canary_then_restores():
    pool = _quiet_pool(outlier_min_samples=4, outlier_min_ms=5.0)
    r0, r1, r2 = pool.replicas
    for _ in range(6):
        for r in (r1, r2):
            pool._observe_latency(r, 10.0)
        pool._observe_latency(r0, 10.0)
    assert all(r.outlier_state == OUTLIER_OK for r in pool.replicas)
    # r0 turns 10x slow: EWMA crosses ratio x median -> soft-ejected
    for _ in range(8):
        pool._observe_latency(r0, 100.0)
    assert r0.outlier_state == OUTLIER_GRAY
    assert r0.outlier_score > pool.outlier_ratio
    assert pool.soft_ejections_total == 1
    assert pool._weight(r0) == pool.outlier_weight
    # recovery: fast samples decay the EWMA under the restore ratio ->
    # canary re-probe at quarter weight, NOT an instant full restore
    while r0.outlier_state == OUTLIER_GRAY:
        pool._observe_latency(r0, 10.0)
    assert r0.outlier_state == OUTLIER_CANARY
    assert pool._weight(r0) == 0.25
    # the canary needs CANARY_OK_REQUIRED good responses to fully restore
    for _ in range(CANARY_OK_REQUIRED + 1):
        pool._observe_latency(r0, 10.0)
    assert r0.outlier_state == OUTLIER_OK
    assert pool.soft_restores_total == 1
    assert pool._weight(r0) == 1.0


def test_canary_relapse_goes_back_to_gray():
    pool = _quiet_pool(outlier_min_samples=4, outlier_min_ms=5.0)
    r0, r1, r2 = pool.replicas
    for _ in range(8):
        pool._observe_latency(r1, 10.0)
        pool._observe_latency(r2, 10.0)
        pool._observe_latency(r0, 100.0)
    assert r0.outlier_state == OUTLIER_GRAY
    while r0.outlier_state == OUTLIER_GRAY:
        pool._observe_latency(r0, 10.0)
    assert r0.outlier_state == OUTLIER_CANARY
    for _ in range(10):  # canary traffic is slow again -> relapse
        pool._observe_latency(r0, 200.0)
    assert r0.outlier_state == OUTLIER_GRAY


def test_last_available_replica_is_never_soft_ejected():
    pool = _quiet_pool(outlier_min_samples=4, outlier_min_ms=5.0)
    r0, r1, r2 = pool.replicas
    for _ in range(6):
        for r in pool.replicas:
            pool._observe_latency(r, 10.0)
    r1.healthy = False
    r2.healthy = False
    for _ in range(10):
        pool._observe_latency(r0, 500.0)
    # r0 is wildly slow but it is all the pool has: thinning it would only
    # slow the pool further
    assert r0.outlier_state == OUTLIER_OK
    assert pool.soft_ejections_total == 0


def test_absolute_floor_blocks_microsecond_noise():
    pool = _quiet_pool(outlier_min_samples=4, outlier_min_ms=20.0)
    r0, r1, r2 = pool.replicas
    # 10x relative spread, but everything is far under the floor: a fast
    # fleet's jitter must not manufacture outliers
    for _ in range(10):
        pool._observe_latency(r0, 5.0)
        pool._observe_latency(r1, 0.5)
        pool._observe_latency(r2, 0.5)
    assert r0.outlier_state == OUTLIER_OK
    assert pool.soft_ejections_total == 0


def test_gray_weight_thins_round_robin_selection():
    pool = _quiet_pool(outlier_min_samples=4)
    r0 = pool.replicas[0]
    r0.outlier_state = OUTLIER_GRAY
    picks = [pool._pick(set()).url for _ in range(300)]
    share = picks.count(r0.url) / len(picks)
    # smooth WRR at weight 0.05 vs 1.0+1.0: expected share ~2.4%
    assert share < 0.10, f"gray replica still got {share:.1%} of picks"
    # the two healthy replicas split the rest evenly (smooth WRR property)
    others = [picks.count(r.url) for r in pool.replicas[1:]]
    assert abs(others[0] - others[1]) <= 2


def test_gray_owner_thinned_in_prefer_order():
    pool = _quiet_pool(outlier_min_samples=4)
    r0, r1, _ = pool.replicas
    r0.outlier_state = OUTLIER_GRAY
    prefer = [r0.url, r1.url]
    picks = [pool._pick(set(), prefer=prefer).url for _ in range(100)]
    # deterministic credit thinning: the gray owner keeps EXACTLY its
    # weight's share of its keyed traffic (the canary trickle), the rest
    # falls to the next-ranked holder
    assert picks.count(r0.url) == round(pool.outlier_weight * 100)
    assert picks.count(r1.url) == 100 - round(pool.outlier_weight * 100)


def test_probe_latency_flags_silent_slow_replica_with_zero_traffic():
    """The ISSUE 14 satellite bugfix: _health_loop used to measure probe
    latency and throw it away. A replica whose /healthz answers 200 but
    slow (starved event loop — the gray signature) must go gray from
    probes alone, before any /detect traffic touches it."""

    async def run():
        replicas, urls = await _with_replicas(3)
        replicas[0].health_delay_s = 0.15
        pool = ReplicaPool(
            urls,
            health_interval_s=0.05,
            outlier_min_samples=3,
            outlier_min_ms=5.0,
        )
        await pool.start()
        try:
            for _ in range(100):
                if pool.replicas[0].outlier_state == OUTLIER_GRAY:
                    break
                await asyncio.sleep(0.05)
            r0 = pool.replicas[0]
            assert r0.outlier_state == OUTLIER_GRAY, (
                f"state={r0.outlier_state} score={r0.outlier_score} "
                f"probe_ewma={r0.probe_ewma.value}"
            )
            assert r0.probe_ewma.value > 100.0
            # zero /detect traffic was needed
            assert all(r.detect_calls == 0 for r in replicas)
            # it is still AVAILABLE (healthz 200): soft ejection, not hard
            assert r0.available(time.monotonic())
            snap = pool.snapshot()
            assert snap["pool_soft_ejections_total"] == 1
            r0_snap = snap["replicas"][0]
            assert r0_snap["outlier_state"] == OUTLIER_GRAY
            assert r0_snap["weight"] == pool.outlier_weight
        finally:
            await pool.stop()
            for r in replicas:
                await r.stop()

    asyncio.run(run())


# ---- budgeted adaptive hedging ---------------------------------------------


def test_adaptive_trigger_tracks_observed_quantile():
    pool = _quiet_pool(adaptive_hedge=True)
    assert pool._hedge_trigger_s() is None  # cold window, no static timer
    for ms in [10.0] * 95 + [200.0] * 5:
        pool._lat_window.append(ms)
    trig = pool._hedge_trigger_s()
    assert trig is not None
    # p95 of 95x10ms + 5x200ms sits at the 10/200 boundary
    assert 0.009 <= trig <= 0.21
    snap = pool.snapshot()
    assert snap["hedge"]["adaptive"] is True
    assert snap["hedge"]["trigger_ms"] == pytest.approx(trig * 1e3)


def test_hedge_loser_is_cancelled_and_not_counted_as_failure():
    async def run():
        replicas, urls = await _with_replicas(2)
        replicas[0].delay_s = 1.0  # alive but drowning
        pool = ReplicaPool(urls, hedge_after_s=0.05, health_interval_s=30.0)
        body = await pool.detect(PAYLOAD)
        assert body["served_by"] == "r1"  # the hedge won
        assert pool.hedges_total == 1
        assert pool.hedge_wins_total == 1
        assert pool.hedge_cancels_total == 1
        # loser exclusion: the cancelled attempt is the hedge's doing, not
        # the replica's — no failure, no ejection progress
        r0 = pool.replicas[0]
        assert r0.consecutive_failures == 0
        assert r0.failures == 0
        # ...but its elapsed time DID feed the latency EWMA (chronic hedge
        # losers must converge toward gray)
        assert r0.req_ewma.samples == 1
        assert r0.req_ewma.value >= 40.0
        # the underlying HTTP request was truly torn down: the replica's
        # handler observed the cancellation
        for _ in range(50):
            if replicas[0].cancelled:
                break
            await asyncio.sleep(0.02)
        assert replicas[0].cancelled == 1
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_hedge_budget_exhaustion_degrades_to_unhedged_not_503():
    async def run():
        replicas, urls = await _with_replicas(2)
        replicas[0].delay_s = 0.3
        pool = ReplicaPool(
            urls,
            hedge_after_s=0.05,
            health_interval_s=30.0,
            hedge_budget=RetryBudget(pct=0.0, min_retries=0),
        )
        t0 = time.perf_counter()
        body = await pool.detect(PAYLOAD)  # primary is r0 (slow)
        elapsed = time.perf_counter() - t0
        # the budget refused the hedge: the request WAITED the primary out
        # and still succeeded — budget exhaustion is never an error
        assert body["served_by"] == "r0"
        assert elapsed >= 0.25
        assert pool.hedges_total == 0
        assert pool.hedge_budget.exhausted_total == 1
        assert pool.failures_total == 0
        snap = pool.snapshot()
        assert snap["pool_hedge_budget_exhausted_total"] == 1
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_hedge_composes_with_affinity_prefer_order():
    async def run():
        replicas, urls = await _with_replicas(3)
        replicas[0].delay_s = 0.5  # the keyed owner is slow
        pool = ReplicaPool(urls, hedge_after_s=0.05, health_interval_s=30.0)
        prefer = [urls[0], urls[2], urls[1]]  # ring-ranked order for a key
        resp = await pool.request("/detect", PAYLOAD, prefer=prefer)
        body = resp.json()
        # primary honored the prefer order (owner first); the hedge's
        # backup came from the SAME ranked order — the next holder, not a
        # random survivor
        assert body["served_by"] == "r2"
        assert pool.hedges_total == 1 and pool.hedge_wins_total == 1
        assert replicas[1].detect_calls == 0
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


def test_adaptive_hedge_end_to_end_masks_slow_replica():
    """Warm the window on fast traffic, then slow one replica: the
    adaptive trigger (observed p95) must fire hedges without any static
    timer being configured."""

    async def run():
        replicas, urls = await _with_replicas(2)
        pool = ReplicaPool(
            urls, adaptive_hedge=True, health_interval_s=30.0
        )
        for _ in range(24):  # warm past HEDGE_MIN_SAMPLES
            await pool.detect(PAYLOAD)
        assert pool.hedges_total == 0 or pool._hedge_trigger_s() is not None
        replicas[0].delay_s = 1.0
        t0 = time.perf_counter()
        for _ in range(2):
            body = await pool.detect(PAYLOAD)
            assert body["served_by"] == "r1"
        assert time.perf_counter() - t0 < 1.0
        assert pool.hedges_total >= 1
        assert pool.failures_total == 0
        await pool.stop()
        for r in replicas:
            await r.stop()

    asyncio.run(run())


# ---- X-Spotter-Replica identity header (satellite) -------------------------


def _build_stub_replica(replica_id: str):
    from spotter_tpu.engine.batcher import MicroBatcher
    from spotter_tpu.serving.detector import AmenitiesDetector
    from spotter_tpu.serving.standalone import make_app
    from spotter_tpu.testing.stub_engine import StubEngine, StubHttpClient

    engine = StubEngine(service_ms=0.0)
    engine.metrics.set_identity(replica_id=replica_id)
    det = AmenitiesDetector(
        engine, MicroBatcher(engine, max_delay_ms=1.0), StubHttpClient()
    )
    return det, make_app(detector=det)


def test_replica_header_at_replica_and_router():
    from spotter_tpu.obs.aggregate import FleetAggregator
    from spotter_tpu.serving import wire
    from spotter_tpu.serving.router import make_router_app

    async def run():
        dets, servers, urls = [], [], []
        for i in range(3):
            det, app = _build_stub_replica(f"rep-{i}")
            server = TestServer(app)
            await server.start_server()
            dets.append(det)
            servers.append(server)
            urls.append(f"http://{server.host}:{server.port}")
        # replica surface: every /detect response names its producer
        async with TestClient(servers[0]) as direct:
            resp = await direct.post(
                "/detect", json={"image_urls": ["http://img/0.jpg"]}
            )
            assert resp.headers[wire.REPLICA_HEADER] == "rep-0"
        pool = ReplicaPool(urls, health_interval_s=0.2)
        app = make_router_app(
            pool, aggregator=FleetAggregator(lambda: [], interval_s=0.0)
        )
        async with TestClient(TestServer(app)) as client:
            # single-owner: the edge echoes the one producing replica
            resp = await client.post(
                "/detect", json={"image_urls": ["http://img/1.jpg"]}
            )
            assert resp.status == 200
            assert resp.headers[wire.REPLICA_HEADER].startswith("rep-")
            # fan-out: every contributing replica id rides, comma-joined
            many = [f"http://img/{i}.jpg" for i in range(12)]
            resp = await client.post("/detect", json={"image_urls": many})
            assert resp.status == 200
            ids = resp.headers[wire.REPLICA_HEADER].split(",")
            assert len(ids) >= 2  # 12 urls over 3 replicas: split for sure
            assert all(i.startswith("rep-") for i in ids)
        await pool.stop()
        for server in servers:
            await server.close()
        for det in dets:
            await det.aclose()

    asyncio.run(run())


# ---- the deterministic chaos matrix ----------------------------------------


def _matrix_params():
    from spotter_tpu.testing.chaos_matrix import GRAY_MATRIX

    return [pytest.param(s, id=s.name) for s in GRAY_MATRIX]


@pytest.mark.parametrize("scenario", _matrix_params())
def test_chaos_matrix_scenario(scenario):
    from spotter_tpu.testing.chaos_matrix import run_scenario

    report = asyncio.run(run_scenario(scenario))
    assert report["ok"], (
        f"scenario {report['name']} failed {report['checks']}: {report}"
    )
